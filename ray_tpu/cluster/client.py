"""ClusterBackend: the in-process runtime for drivers and workers.

Implements the same Backend surface as ``core.local_backend.LocalBackend``
over the cluster's control plane (head) and data plane (shm stores + node
agents) — task submission with cluster scheduling, direct actor calls
(caller → actor worker RPC, no agent hop: the direct actor transport of
``direct_actor_task_submitter.h``), object put/get with pull-based
transfer, and lineage-based re-execution: if the node that was computing a
task dies, the owner resubmits the task spec elsewhere
(``object_recovery_manager.h:41``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Sequence

from ray_tpu._native.shm_store import ShmStore, StoreFullError
from ray_tpu.cluster.rpc import ConnectionLost, RpcClient, RpcServer
from ray_tpu.core import ids
from ray_tpu.core import serialization as ser
from ray_tpu.core.object_ref import (
    ActorError,
    GetTimeoutError,
    ObjectRef,
    ObjectLostError,
    TaskError,
)
from ray_tpu.core.config import config
from ray_tpu.core.resources import demand_of
from ray_tpu.util import failpoints
from ray_tpu.util import metrics as _metrics


# Poll-again sentinel: a fetch hit only stale/dead locations; the oid
# stays pending and the next location round decides (recovery, head
# fallback, or a fresh copy).
_REFETCH = object()


def _is_preemption_loss(cause) -> bool:
    """Was this loss caused by a planned drain / preemption? Such losses
    are exempt from retry budgets (Ray's preemption exemption: work lost
    to a preempted node does not consume ``max_retries``)."""
    c = (cause or "").lower()
    return c.startswith("drained") or "draining" in c or "preempt" in c


class _GetError:
    """An exception captured for one ref of a multi-ref get, deferred so
    errors raise in ref order."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class _PullManager:
    """Admission control for chunked remote pulls (the client-side analog
    of ``src/ray/object_manager/pull_manager.h:48``): total in-flight
    pulled bytes are capped per process, and blocked pulls are admitted
    strictly by priority class — explicit ``get`` before ``wait``
    prefetches before task-argument materialization — FIFO within a
    class. A single pull larger than the cap is admitted alone (a huge
    object must not deadlock)."""

    def __init__(self):
        self._cv = threading.Condition()
        self._inflight = 0
        self._waiters: list = []  # heap of (priority, seq)
        self._seq = 0

    def acquire(self, nbytes: int, priority: int) -> None:
        import heapq

        with self._cv:
            seq = self._seq
            self._seq += 1
            heapq.heappush(self._waiters, (priority, seq))
            while True:
                cap = config.pull_max_inflight_bytes
                at_front = self._waiters[0] == (priority, seq)
                fits = self._inflight == 0 or \
                    self._inflight + nbytes <= cap
                if at_front and fits:
                    heapq.heappop(self._waiters)
                    self._inflight += nbytes
                    self._cv.notify_all()
                    return
                self._cv.wait(0.5)

    def release(self, nbytes: int) -> None:
        with self._cv:
            self._inflight -= nbytes
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return {"inflight_bytes": self._inflight,
                    "queued": len(self._waiters)}


class _OwnerService:
    """RPC surface of a client's owner directory (the per-worker half of
    the reference's ownership protocol: executing workers report result
    locations to the owner; borrowers resolve/wait against the owner)."""

    def __init__(self, backend: "ClusterBackend"):
        self._b = backend

    def rpc_owner_add_location(self, oid, node_id, address, store_path,
                               is_error=False, size=0, attr=None):
        self._b._owner_record(oid, node_id, address, store_path,
                              is_error, size, attr)
        return True

    def rpc_owner_wait_locations(self, oids, timeout=None):
        return self._b.owner_wait_locations(oids, timeout)


class ClusterBackend:
    def __init__(self, head_address: str, *, node_id: str | None = None,
                 store_path: str | None = None, agent_address: str | None = None,
                 process_kind: str = "d"):
        import os

        # Reconnect window: a head restart (GCS FT) retries instead of
        # failing in-flight location/ref/schedule calls.
        self.head = RpcClient(
            head_address,
            reconnect_window=config.head_reconnect_window_s,
        )
        self.head_address = head_address
        self._agent_address = agent_address
        if node_id is None:
            nodes = [n for n in self.head.call("nodes") if n["Alive"]]
            if not nodes:
                raise RuntimeError("cluster has no alive nodes")
            node_id, store_path = nodes[0]["NodeID"], nodes[0]["StorePath"]
            self._agent_address = nodes[0]["Address"]
        self.node_id = node_id
        self.store_path = store_path
        # "d" = driver (survives node death), "w" = worker (dies with node).
        self.client_id = (
            f"{process_kind}:{node_id}:{os.getpid()}:{os.urandom(3).hex()}"
        )
        self.store = ShmStore(store_path)
        self._node_clients: dict[str, RpcClient] = {}
        self._worker_clients: dict[str, RpcClient] = {}
        self._actor_cache: dict[str, dict] = {}
        self._lock = threading.Lock()
        # Owner-side lineage: oid -> task spec, for re-execution on loss.
        self._lineage: dict[str, dict] = {}
        # Actor-creation lineage: actor_id -> creation spec, until the
        # actor registers. A ctor lost WITH its node (killed before the
        # agent could dispatch/register) has no worker/agent left to
        # report anything — the creating driver resubmits, exactly like
        # task lineage (safe: the assigned node is dead).
        self._actor_creations: dict[str, dict] = {}
        # Pending actor-task results: oid -> actor_id (for fail-fast when
        # the actor dies with calls in flight).
        self._actor_tasks: dict[str, str] = {}
        # Packaged runtime envs, memoized by the user dict's canonical
        # JSON (reference packages once per job; we package once per
        # distinct env per driver — content re-hashed only on first use).
        self._rtenv_cache: dict[str, dict] = {}
        # Function-table export memo: func -> (hash, closure_refs)
        # (reference function_manager export-once semantics).
        import weakref

        self._fn_exports = weakref.WeakKeyDictionary()
        self._fn_keys: set[str] = set()  # for close-time KV cleanup
        self._pins: dict[str, Any] = {}  # zero-copy views we hold alive
        # Set by the worker process: (on_block, on_unblock) callbacks that
        # tell the node agent to release/reacquire this task's resources
        # while we block in get() (nested-task deadlock avoidance).
        self._block_hooks: tuple | None = None
        # Process-local ref counts feeding the head's distributed table
        # (reference_count.h analog): transitions 0->1 / 1->0 are batched
        # to the head by a flusher thread; ObjectRef finalizers only touch
        # dicts (no RPC on the GC path).
        # RLock: _deref runs from weakref finalizers, which GC may invoke
        # on a thread that already holds this lock mid-allocation.
        self._ref_lock = threading.RLock()
        self._local_refs: dict[str, int] = {}
        self._dirty_add: set[str] = set()
        self._dirty_remove: set[str] = set()
        # Batched head location reports: put_with_id appends; the ref
        # flusher ships them (always BEFORE ref updates, so container
        # holds for nested refs reach the head ahead of any borrow
        # release they must outlive).
        self._loc_dirty: list = []
        self._ref_cv = threading.Condition(self._ref_lock)
        # Serializes flush I/O: flush_refs() must not return while another
        # thread's ref_update RPC is still in flight (borrower-handoff
        # ordering depends on add-before-task-end). Holding it across
        # the RPC is this lock's entire job — nothing else contends it
        # except a concurrent flush, which must wait anyway.
        self._flush_io_lock = threading.Lock()  # analyze: allow-blocking
        self._closed = False
        threading.Thread(target=self._ref_flush_loop, daemon=True).start()
        # Pipelined submission (direct_task_transport.h:57 in spirit):
        # submit_task enqueues; the submitter thread drains bursts and
        # (a) pushes default-strategy specs straight to THIS client's own
        # node under strict admission — the decentralized prefer-local
        # half of the reference's hybrid policy, no head RPC at all — then
        # (b) places whatever the local node rejected (plus SPREAD/
        # affinity/PG specs) with ONE schedule_batch call. Natural
        # batching: a lone task dispatches immediately; under load
        # batches grow.
        import collections as _collections

        self._submit_q: "_collections.deque[dict]" = _collections.deque()
        self._submit_cv = threading.Condition()
        self._dispatching = 0  # specs popped from the queue, mid-dispatch
        self._retry_heap: list = []  # (due, seq, spec) — shared retry timer
        self._retry_seq = 0
        # (ts, {NodeID: info}) head node-table snapshot shared by the
        # loss-recovery paths (_maybe_recover, actor recovery, parked-
        # affinity fallback); refreshed at most ~1/s so a mass-recovery
        # storm costs one `nodes` RPC per second, not one per spec.
        self._nodes_cache: tuple = (-1e9, None)
        # Per-oid throttle for restore-from-spill-URI attempts (bounded;
        # see _try_restore_spilled).
        self._restore_attempts: dict[str, float] = {}
        # Owner-distributed object directory (reference ownership model:
        # reference_count.h:61 holds per-object state on the OWNING worker,
        # ownership_based_object_directory.h resolves locations from
        # owners, not the GCS). This process is the authoritative location
        # directory for every object it creates (put / outputs of tasks it
        # submits): executing workers report result locations straight to
        # the owner, get()/wait() on self-owned refs block on this local
        # table with NO head RPC, and borrowers long-poll the owner's
        # server. The head keeps object->owner routing plus its own
        # asynchronously-batched location view as the FT fallback when an
        # owner dies (owner death = objects lost, reference semantics).
        self._owned: dict[str, dict] = {}
        # RLock for the same reason as _ref_lock: _deref runs from
        # weakref finalizers, which GC may invoke on a thread already
        # holding this lock mid-allocation (e.g. inside
        # owner_wait_locations building its result dict) — a plain Lock
        # self-deadlocks there and stalls every location operation.
        self._owned_lock = threading.RLock()
        self._owned_cv = threading.Condition(self._owned_lock)
        self._dead_owners: set[str] = set()
        self._owner_clients: dict[str, RpcClient] = {}
        host = (self._agent_address or "127.0.0.1:0").rsplit(":", 1)[0]
        try:
            self._owner_server = RpcServer(_OwnerService(self), host=host)
        except OSError:
            self._owner_server = RpcServer(_OwnerService(self))
        self.owner_addr = self._owner_server.address
        # Chaos source identity: worker processes carry their NODE's
        # identity (the agent address) so node-keyed partition rules cut
        # worker-originated traffic too; drivers carry their own
        # owner-directory address (an endpoint of their own).
        self._chaos_tag = (
            self._agent_address
            if process_kind == "w" and self._agent_address
            else self.owner_addr)
        self.head.chaos_src = self._chaos_tag
        # Pull admission (get > wait > args, bounded in-flight bytes).
        self._pulls = _PullManager()
        self._pull_prio = threading.local()
        self._prefetching: set[str] = set()
        # task_id -> borrowed oids held locally until borrow registration
        # reaches the head (so callers may drop arg handles immediately
        # even though dispatch is now asynchronous).
        self._submit_holds: dict[str, list[str]] = {}
        threading.Thread(target=self._submit_loop, daemon=True).start()
        self.process_kind = process_kind
        if process_kind == "d":
            # Drivers stream worker stdout/stderr from the head via the
            # pubsub LOGS channel. Subscribe SYNCHRONOUSLY so lines
            # emitted right after connect can't race the poll thread's
            # startup and publish to zero subscribers.
            try:
                self.head.call(
                    "pubsub_subscribe", "logs:" + self.client_id, "LOGS")
                subscribed = True
            except Exception:
                subscribed = False  # the poll loop re-subscribes
            threading.Thread(
                target=self._log_poll_loop, args=(subscribed,),
                daemon=True,
            ).start()
        if process_kind != "w":
            # Driver/proxy-side spans (submit:, serve.http, serve.route,
            # serve.stream) have no workerproc event flusher to carry
            # them — without this daemon the head's flight recorder
            # assembles traces missing their roots. Workers skip it:
            # their spans ride the agent event batch, node-attributed.
            threading.Thread(target=self._span_flush_loop,
                             daemon=True).start()

    # -- plumbing ----------------------------------------------------------

    def _span_flush_loop(self):
        from ray_tpu.util import metrics as _metrics

        while not self._closed:
            time.sleep(0.5)
            try:
                self._flush_spans()
            except Exception:
                _metrics.count_loop_restart("client.span_flush")

    def _flush_spans(self):
        """Ship this process's finished spans (and its span-buffer
        truncation count) to the head's flight recorder."""
        from ray_tpu.util import tracing

        # A closed client must not keep draining the process-global
        # span buffer: the next backend (or a local collect()) owns it.
        if self._closed or not tracing.is_enabled():
            return
        spans = tracing.drain()
        dropped = tracing.drain_dropped()
        if not spans and not dropped:
            return
        with tracing.suppressed():
            try:
                self.head.call(
                    "report_spans", spans, "driver:" + self.client_id,
                    dropped=dropped, timeout=10.0)
            except Exception:
                # The batch is gone (drain pops); count it as dropped
                # rather than silently losing spans AND their counter.
                tracing.requeue_dropped(dropped + len(spans))
                raise

    def _node_client(self, address: str) -> RpcClient:
        with self._lock:
            c = self._node_clients.get(address)
            if c is None:
                c = self._node_clients[address] = RpcClient(address)
                c.chaos_src = self._chaos_tag
            return c

    def _worker_client(self, address: str) -> RpcClient:
        with self._lock:
            c = self._worker_clients.get(address)
            if c is None:
                c = self._worker_clients[address] = RpcClient(address)
                c.chaos_src = self._chaos_tag
            return c

    def _agent_client(self) -> RpcClient:
        """RPC client to THIS node's agent (spill requests, etc.)."""
        if self._agent_address is None:
            for n in self.head.call("nodes"):
                if n["NodeID"] == self.node_id:
                    self._agent_address = n["Address"]
                    break
            else:
                raise RuntimeError(f"node {self.node_id} not in directory")
        return self._node_client(self._agent_address)

    # -- ref counting ------------------------------------------------------

    def _incref(self, oid: str) -> None:
        with self._ref_lock:
            n = self._local_refs.get(oid, 0)
            self._local_refs[oid] = n + 1
            if n == 0:
                if oid in self._dirty_remove:
                    self._dirty_remove.discard(oid)
                else:
                    self._dirty_add.add(oid)
                self._ref_cv.notify_all()

    def make_ref(self, oid: str, owner: str | None = None) -> ObjectRef:
        self._incref(oid)
        # The ref carries its owner's directory address: any borrower that
        # deserializes it can resolve locations straight from the owner.
        ref = ObjectRef(oid, owner if owner is not None else self.owner_addr)
        import weakref

        weakref.finalize(ref, self._deref, oid)
        return ref

    def on_ref_deserialized(self, oid: str, owner: str) -> ObjectRef:
        """Unpickle hook: this process becomes a holder (borrower)."""
        return self.make_ref(oid, owner)

    def _deref(self, oid: str) -> None:
        with self._ref_lock:
            n = self._local_refs.get(oid, 0) - 1
            if n > 0:
                self._local_refs[oid] = n
                return
            self._local_refs.pop(oid, None)
            # Always send the remove, even when the matching add was never
            # flushed: the head treats a remove for an unknown oid as
            # "held-and-released between flushes" and frees it — otherwise
            # a pinned primary copy with no registered holder would be
            # immortal.
            self._dirty_add.discard(oid)
            self._dirty_remove.add(oid)
            self._ref_cv.notify_all()
        self._lineage.pop(oid, None)  # owner dropped it: no recovery needed
        with self._owned_cv:
            self._owned.pop(oid, None)

    def _ref_flush_loop(self) -> None:
        while True:
            with self._ref_cv:
                while (
                    not self._dirty_add and not self._dirty_remove
                    and not self._loc_dirty and not self._closed
                ):
                    self._ref_cv.wait(0.5)
                if self._closed:
                    return
            time.sleep(0.02)  # coalesce bursts into one RPC
            try:
                self.flush_refs()
            except Exception:
                # The flusher must survive anything one flush throws
                # (chaos failpoints, a head mid-restart edge): a dead
                # flusher silently stops all ref/location reporting for
                # the rest of the process's life.
                _metrics.count_loop_restart("client.ref_flush")
                continue

    def flush_refs(self) -> None:
        """Push pending holder add/removes to the head. Workers call this
        synchronously before reporting task end so borrower registration
        can never lose the race against the borrow release. The io lock
        makes that guarantee hold even when the background flusher already
        popped the dirty sets: we wait for its RPC to finish."""
        failpoints.hit("client.flush_refs.before")
        with self._flush_io_lock:
            with self._ref_lock:
                if not self._dirty_add and not self._dirty_remove \
                        and not self._loc_dirty:
                    return
                add, self._dirty_add = list(self._dirty_add), set()
                remove, self._dirty_remove = list(self._dirty_remove), set()
                locs, self._loc_dirty = self._loc_dirty, []
            # Locations FIRST: an add_locations batch carries container
            # holds for nested refs (contained=...), which must reach the
            # head before any ref remove flushed after it can zero them.
            if locs:
                try:
                    self.head.call("add_locations", locs)
                except (ConnectionLost, OSError):
                    # Restore EVERYTHING popped — the ref batches too:
                    # dropping them would leak holders (lost removes) or
                    # free held objects (lost adds), same invariant as
                    # the ref_update failure path below.
                    with self._ref_lock:
                        if not self._closed:
                            self._loc_dirty = locs + self._loc_dirty
                            self._dirty_add.update(add)
                            self._dirty_remove.update(remove)
                    return  # keep add-before-remove ordering on retry
            try:
                self.head.call("ref_update", self.client_id, add, remove)
            except (ConnectionLost, OSError):
                # Transient failure: requeue the batch — dropping it would
                # leak holders (lost removes) or free held objects (lost
                # adds).
                with self._ref_lock:
                    if not self._closed:
                        self._dirty_add.update(add)
                        self._dirty_remove.update(remove)

    # -- owner directory ---------------------------------------------------

    def _owner_record(self, oid: str, node_id: str, address: str,
                      store_path: str, is_error: bool = False,
                      size: int = 0, attr: dict | None = None) -> None:
        """A copy of an object WE own appeared on ``node_id``."""
        with self._owned_cv:
            e = self._owned.setdefault(
                oid, {"nodes": {}, "error": False, "size": 0})
            e["nodes"][node_id] = (address, store_path)
            e["error"] = e["error"] or bool(is_error)
            e["size"] = max(e["size"], int(size))
            if attr and "attr" not in e:
                # Creation attribution (owner/task/callsite): first
                # writer wins — replica reports carry no attr.
                e["attr"] = dict(attr)
            self._owned_cv.notify_all()

    def _owner_drop(self, oid: str, node_ids) -> None:
        with self._owned_cv:
            e = self._owned.get(oid)
            if not e:
                return
            for nid in node_ids:
                e["nodes"].pop(nid, None)
            if not e["nodes"]:
                self._owned.pop(oid, None)

    def _owner_knows(self, oid: str) -> bool:
        """Is this oid either resolvable or still expected (a pending
        output of a task/actor call we submitted)? False = we dropped
        our handle: a borrower should resolve through the head instead."""
        if oid in self._owned or oid in self._lineage \
                or oid in self._actor_tasks:
            return True
        # Streaming indices > 1 share the index-0 spec's lineage entry.
        return ids.object_id_for(oid[:32], 0) in self._lineage

    def owner_wait_locations(self, oids, timeout=None) -> dict:
        """Head-``wait_locations`` semantics against the local owner
        table: block until at least one of ``oids`` has a location (or
        timeout); returns {oid: {"nodes": [(nid, addr, store_path)],
        "error": bool}} for every currently-resolvable oid. Oids this
        owner no longer tracks come back as {"forgotten": True} so a
        borrower falls over to the head's FT view immediately."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._owned_cv:
            while True:
                found = {}
                pending_known = False
                for oid in oids:
                    e = self._owned.get(oid)
                    if e and e["nodes"]:
                        found[oid] = {
                            "nodes": [(nid, a, sp) for nid, (a, sp)
                                      in e["nodes"].items()],
                            "error": e["error"],
                        }
                    elif self._owner_knows(oid):
                        pending_known = True
                    else:
                        found[oid] = {"forgotten": True}
                if found or not pending_known:
                    return found
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return {}
                self._owned_cv.wait(
                    1.0 if remaining is None else min(remaining, 1.0))

    def _owner_client(self, addr: str) -> RpcClient:
        with self._lock:
            c = self._owner_clients.get(addr)
            if c is None:
                c = self._owner_clients[addr] = RpcClient(addr, timeout=30.0)
                c.chaos_src = self._chaos_tag
            return c

    def _report_location(self, oid: str, owner: str | None,
                         is_error: bool, size: int,
                         attr: dict | None = None) -> None:
        """Tell the object's owner a copy now lives on this node. Local
        record when we ARE the owner (the common case: the driver's own
        puts); one direct RPC worker->owner otherwise — the head is not
        on this path at all."""
        if not owner or owner == self.owner_addr:
            self._owner_record(oid, self.node_id, self._agent_address or "",
                               self.store_path or "", is_error, size, attr)
            return
        if owner in self._dead_owners:
            return
        try:
            self._owner_client(owner).call(
                "owner_add_location", oid, self.node_id,
                self._agent_address or "", self.store_path or "",
                is_error, size, attr, timeout=10.0)
        except (ConnectionLost, OSError):
            # Owner gone: its objects are recoverable only through the
            # head's batched view / lineage. Best-effort by design.
            self._dead_owners.add(owner)

    # -- object plane ------------------------------------------------------

    def put_with_id(self, oid: str, value: Any, is_error: bool = False,
                    owner: str | None = None) -> None:
        from ray_tpu.core import attribution

        flag = b"E" if is_error else b"V"
        contained: list[str] = []
        # Put-time attribution (owner worker id, creating task, optional
        # callsite) rides the store-entry meta so any node holding a
        # replica can answer "whose bytes are these" without the head.
        attr = attribution.make(
            self.client_id,
            default_task="driver" if self.process_kind == "d" else "worker")
        meta, chunks = ser.serialize(value, found_refs=contained,
                                     extra_meta={"attr": attr})
        size = ser.total_size(chunks)
        for attempt in range(8):
            try:
                self.store.put(oid, chunks, flag + meta)
                break
            except StoreFullError:
                # Ask this node's agent to spill cold objects to disk and
                # retry (create-request backpressure + spill orchestration,
                # local_object_manager.h:110 analog).
                try:
                    freed = self._agent_client().call(
                        "spill", size + config.spill_headroom_bytes,
                        timeout=60.0,
                    )
                except (ConnectionLost, OSError):
                    freed = 0
                if freed <= 0:
                    if attempt >= 6:
                        raise
                    # Nothing spillable, but a free may be IN FLIGHT: the
                    # head already forgot a dropped object (so it's not a
                    # spill candidate) while the fanout delete hasn't
                    # reached this store yet. Wait it out, then retry.
                    time.sleep(0.05 * (attempt + 1))
        else:
            raise StoreFullError(f"object {oid[:16]}… ({size} bytes)")
        # Primary copy: protect from LRU eviction until the cluster
        # ref-counter frees it (spilling is still allowed — data survives).
        self.store.pin(oid)
        # Ownership split: the owner learns the location synchronously
        # (worker->owner direct, or a lock-free local record when we own
        # it) — that is what unblocks a waiting get(). The head's copy is
        # batched through the ref flusher: it serves FT fallback, free
        # fanout, and spill candidacy, none of which need sync latency.
        self._report_location(oid, owner, is_error, size, attr)
        with self._ref_lock:
            self._loc_dirty.append(
                (oid, self.node_id, is_error, size, contained,
                 owner or self.owner_addr, attr))
            self._ref_cv.notify_all()

    def put(self, value: Any) -> ObjectRef:
        oid = ids.new_object_id()
        self.put_with_id(oid, value)
        return self.make_ref(oid)

    def _read_local(self, oid: str):
        """Returns (value,) or None if the object isn't in the local store.
        (The 1-tuple disambiguates a stored None from a miss.)"""
        got = self.store.get(oid)
        if got is None:
            return None
        data, meta = got
        try:
            value = self._decode(meta, data)
        except BaseException:
            self.store.release(oid)
            raise
        self._scope_pin(oid, value, ser.num_buffers(meta[1:]))
        return (value,)

    def _scope_pin(self, oid: str, value: Any, nbufs: int) -> None:
        """Hold the store refcount (zero-copy pin) only while deserialized
        views into the segment can still be alive.

        * no out-of-band buffers: nothing points into the segment — release
          immediately;
        * numpy arrays found in the value: release when they are all
          collected (plasma parity: buffer lifetime pins the object);
        * buffers but no trackable arrays: keep the pin for the backend's
          lifetime (rare; conservative).
        """
        if nbufs == 0:
            self.store.release(oid)
            return
        import weakref

        import numpy as np

        arrays: list = []

        def walk(v, depth=0):
            if depth > 4:
                return
            if isinstance(v, np.ndarray):
                arrays.append(v)
            elif isinstance(v, dict):
                for x in v.values():
                    walk(x, depth + 1)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    walk(x, depth + 1)

        walk(value)
        if not arrays:
            self._pins[oid] = True
            return
        remaining = {"n": len(arrays)}
        store = self.store

        def on_dead():
            remaining["n"] -= 1
            if remaining["n"] == 0:
                try:
                    store.release(oid)
                except Exception:
                    pass

        for a in arrays:
            weakref.finalize(a, on_dead)
        # The recursive ``walk`` closure is a cycle (it closes over its own
        # cell), which keeps THIS list — and so every array in it — alive
        # until a gc pass. Drop the strong refs now so the finalizers fire
        # on plain refcount death and the store pin releases promptly.
        arrays.clear()

    @staticmethod
    def _decode(meta: bytes, data):
        flag, ser_meta = meta[:1], meta[1:]
        value = ser.deserialize(ser_meta, data)
        if flag == b"E":
            raise value
        return value

    def _fetch_remote(self, oid: str, locations: list) -> Any:
        last_err: Exception | None = None
        for node_id, address, _store_path in locations:
            if node_id == self.node_id:
                boxed = self._read_local(oid)
                if boxed is not None:
                    return boxed[0]
                # Not in the local segment but the directory says it's on
                # this node: it was spilled — the agent restores/serves it.
            try:
                got = self._pull_object(address, oid)
            except (ConnectionLost, OSError, ObjectLostError) as e:
                # ObjectLostError: this replica vanished mid-pull (evicted
                # + unspilled); the next location may still be intact.
                last_err = e
                continue
            if got is None:
                continue
            meta, data = got
            return self._decode(meta, data)
        raise ObjectLostError(
            f"object {oid[:16]}… not retrievable from {len(locations)} "
            f"location(s): {last_err}"
        )

    # Node-to-node transfer tuning (object_manager.h:117, push_manager.h:29
    # analog — pull-based here): objects above the whole-fetch max stream
    # in bounded chunks with a capped number in flight, so no RPC frame
    # exceeds ~chunk size and peak extra memory is a few chunks (not 2x
    # size as with a single pickled frame). 4 MiB × 8 in flight keeps a
    # 64 MiB arg at 2 serial rounds instead of 16. All three knobs read
    # the config registry AT CALL TIME so env/override changes apply
    # without re-importing (RAY_TPU_TRANSFER_*).

    PULL_GET, PULL_WAIT, PULL_ARGS = 0, 1, 2

    def _pull_priority(self) -> int:
        return getattr(self._pull_prio, "v", self.PULL_GET)

    def pull_priority_override(self, prio: int):
        """Context manager: pulls on this thread use the given class
        (workers lower arg-materialization below explicit gets)."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            prev = getattr(self._pull_prio, "v", None)
            self._pull_prio.v = prio
            try:
                yield
            finally:
                if prev is None:
                    del self._pull_prio.v
                else:
                    self._pull_prio.v = prev

        return cm()

    def _pull_object(self, address: str, oid: str):
        """(meta, data) from a peer node: ONE round trip for small objects
        (data inlined in the info reply), bounded chunked streaming for
        large ones — the latter admitted through the pull manager
        (priority get > wait > args, total in-flight bytes capped:
        pull_manager.h:48 admission control)."""
        chunk_size = config.transfer_chunk_bytes
        client = self._node_client(address)
        info = client.call(
            "fetch_object_info", oid, config.transfer_whole_fetch_max_bytes)
        if info is None:
            return None
        meta, size, inline = info
        if inline is not None:
            return meta, inline
        self._pulls.acquire(size, self._pull_priority())
        try:
            # Mid-size objects: ONE streaming request (server pipelines
            # the chunk frames back-to-back — no per-chunk round trip).
            # Huge objects still fan out over the parallel pull pool so
            # multiple TCP connections share the copy work.
            n_chunks = (size + chunk_size - 1) // chunk_size
            if n_chunks <= config.transfer_stream_max_chunks:
                return meta, self._pull_streamed(
                    client, oid, size, chunk_size)
            return meta, self._pull_chunked(client, oid, size, chunk_size)
        finally:
            self._pulls.release(size)

    def _pull_streamed(self, client, oid: str, size: int, chunk_size: int):
        buf = bytearray(size)
        off = 0
        for piece in client.call_stream(
                "fetch_object_stream", oid, size, chunk_size):
            buf[off:off + len(piece)] = piece
            off += len(piece)
        if off != size:
            raise ObjectLostError(
                f"stream of {oid[:16]}… ended early at {off}/{size}")
        return buf

    def _pull_chunked(self, client, oid: str, size: int, chunk_size: int):
        buf = bytearray(size)
        offsets = list(range(0, size, chunk_size))

        def pull_chunk(off: int):
            # Per-thread pooled connections cap the frames in flight
            # toward this node at the pull-pool's thread count.
            length = min(chunk_size, size - off)
            chunk = client.call("fetch_object_chunk", oid, off, length)
            if chunk is None or len(chunk) != length:
                raise ObjectLostError(
                    f"chunk [{off}:{off + length}) of {oid[:16]}… missing"
                )
            buf[off:off + length] = chunk

        futs = [self._pull_pool().submit(pull_chunk, o) for o in offsets]
        err = None
        for fut in futs:
            try:
                fut.result()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = err or e
        if err is not None:
            raise err
        return buf

    def _pull_pool(self):
        """One long-lived chunk-pull executor per backend: its threads
        keep their pooled TCP connections warm across pulls."""
        pool = getattr(self, "_chunk_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                pool = getattr(self, "_chunk_pool", None)
                if pool is None:
                    pool = self._chunk_pool = ThreadPoolExecutor(
                        config.transfer_pull_concurrency,
                        thread_name_prefix="chunk-pull")
        return pool

    def _nodes_snapshot(self, max_age_s: float = 1.0) -> dict | None:
        """{NodeID: info} head node-table snapshot, cached ``max_age_s``:
        the loss-recovery paths poll repeatedly, so ≤1s-stale liveness
        only defers a recovery to the next poll — it never recovers a
        task whose node is actually alive (dead nodes stay dead; node
        ids are never reused). Returns None when the head is
        unreachable (callers treat that as "retry later")."""
        now = time.monotonic()
        ts, nodes = self._nodes_cache
        if nodes is None or now - ts > max_age_s:
            try:
                nodes = {n["NodeID"]: n for n in self.head.call("nodes")}
            except (ConnectionLost, OSError):
                return None
            self._nodes_cache = (now, nodes)
        return nodes

    def _try_restore_spilled(self, oid: str) -> bool:
        """Remote-spill recovery: if the head holds a spill-URI record
        for this object, have it restored onto a live node instead of
        recomputing (or losing) it. Throttled per oid so the location
        poll can call this every round without hammering the head.
        On success the restored location is recorded into the local
        owner table so the next poll round resolves without an RPC."""
        now = time.monotonic()
        last = self._restore_attempts.get(oid, 0.0)
        if now - last < 2.0:
            return False
        if len(self._restore_attempts) > 4096:
            self._restore_attempts.clear()
        self._restore_attempts[oid] = now
        try:
            loc = self.head.call("restore_spilled", oid, timeout=45.0)
        except (ConnectionLost, OSError):
            return False
        if not loc:
            return False
        node_id, address, store_path = loc
        self._owner_record(oid, node_id, address, store_path)
        return True

    def _maybe_recover(self, oid: str) -> bool:
        """Lineage reconstruction: resubmit the creating task if its node
        died before the object appeared — unless a REMOTE-SPILLED copy
        of it survives, in which case the head restores it from the
        spill URI and no recomputation happens. Returns True if
        recovery was initiated either way."""
        spec = self._lineage.get(oid)
        if spec is None:
            # Streaming indices > 0 are synthesized by the generator and
            # never entered the lineage table themselves — recover
            # through the stream's index-0 spec (whole-task re-run; the
            # re-execution re-stores every index).
            tid, idx = ids.task_of_object(oid)
            if idx > 0:
                root = self._lineage.get(ids.object_id_for(tid, 0))
                if root is not None and \
                        root.get("num_returns") == "streaming":
                    spec = root
        if spec is None:
            return False
        assigned = spec.get("assigned_node")
        if assigned is None:
            return False  # not yet placed; the pending-retry thread owns it
        nodes = self._nodes_snapshot()
        if nodes is None:
            return False  # head unreachable: the get loop retries
        info = nodes.get(assigned, {})
        if info.get("Alive"):
            return False  # still computing (a DRAINING node finishes work)
        # The creating node is dead — but if the object was spilled to a
        # remote target, restore beats recompute (cheaper, and works for
        # results whose inputs are gone too).
        if self._try_restore_spilled(oid):
            return True
        # Preemption exemption: a task lost to a drained/preempted node
        # re-executes WITHOUT consuming retries_left — planned node
        # departure is the platform's fault, not the task's.
        exempt = _is_preemption_loss(info.get("DeathCause"))
        if spec.get("retries_left", 0) <= 0 and not exempt:
            return False
        if not exempt:
            spec["retries_left"] -= 1
        # Soft affinity on recovery: the pinned node is gone, so let the
        # scheduler place the retry anywhere feasible.
        spec["sinfo"]["node_affinity"] = None
        failpoints.hit("client.recover.before_resubmit")
        try:
            self._submit_spec(spec)
        except (ValueError, TimeoutError):
            return False
        return True

    def _check_actor_alive(self, oid: str, refresh: bool = True) -> None:
        """A pending actor-task result can never appear if the actor died —
        fail fast (RayActorError parity). If the actor RESTARTED and this
        call was lost with it, replay the call within the actor's
        max_task_retries budget (direct_actor_task_submitter retry analog).
        ``refresh=False`` trusts the actor cache a caller just refreshed
        (wait()'s per-round dedup across refs of one actor)."""
        entry = self._actor_tasks.get(oid)
        if entry is None:
            return
        actor_id = entry["actor_id"]
        info = self._actor_info(actor_id, refresh=refresh)
        if info["state"] == "DEAD":
            for o in entry.get("oids", [oid]):
                self._actor_tasks.pop(o, None)
            raise ActorError(
                f"actor {actor_id} died before this call completed: "
                f"{info.get('death_cause')}"
            )
        if info["state"] != "ALIVE":
            return  # restarting: keep waiting
        if info.get("num_restarts", 0) > entry["incarnation"]:
            # The call was in flight across a restart: its execution (and
            # queued successors) died with the old worker. Calls lost to
            # a drain-migration replay budget-free (preemption exemption,
            # mirroring the task-retry exemption).
            exempt = _is_preemption_loss(info.get("restart_cause"))
            if entry["retries_left"] == 0 and not exempt:
                for o in entry.get("oids", [oid]):
                    self._actor_tasks.pop(o, None)
                raise ActorError(
                    f"actor {actor_id} restarted and the call was lost "
                    f"(max_task_retries exhausted)"
                )
            if entry["retries_left"] > 0 and not exempt:
                entry["retries_left"] -= 1
            entry["incarnation"] = info["num_restarts"]
            spec = entry["spec"]
            self._register_borrows(spec, info["node_id"])
            try:
                self._worker_client(info["address"]).call(
                    "push_actor_task", spec
                )
            except (ConnectionLost, OSError):
                self._end_borrows(spec)  # next get() round retries again
                entry["incarnation"] -= 1  # didn't actually replay

    def _poll_locations(self, window, owner_of, head_oids: set,
                        sweep_head: bool, timeout: float = 1.0) -> dict:
        """One location-poll round: self-owned oids block on the LOCAL
        owner table (zero RPCs — the common case: a driver getting its
        own tasks' results), borrowed oids long-poll their owner's
        directory server directly, and only oids with no/dead/forgetful
        owner touch the head (plus a whole-window head sweep every 4th
        round as the safety net for owner-unaware reporters). Returns
        {oid: {"nodes": [...], "error": bool}} for resolvable oids;
        mutates ``head_oids`` as owners die or disavow oids."""
        mine, by_owner, to_head = [], {}, []
        for oid in window:
            owner = owner_of.get(oid) or ""
            if oid in head_oids or not owner \
                    or owner in self._dead_owners:
                to_head.append(oid)
            elif owner == self.owner_addr:
                mine.append(oid)
            else:
                by_owner.setdefault(owner, []).append(oid)
        if sweep_head:
            to_head = list(window)

        jobs = []  # (kind, oids, thunk)
        if mine:
            jobs.append(("local", mine,
                         lambda o=mine: self.owner_wait_locations(
                             o, timeout)))
        for owner, oids in by_owner.items():
            jobs.append((owner, oids,
                         lambda ow=owner, o=oids: self._owner_client(
                             ow).call("owner_wait_locations", o, timeout,
                                      timeout=timeout + 30.0)))
        if to_head:
            jobs.append(("head", to_head,
                         lambda o=to_head: self.head.call(
                             "wait_locations", o, timeout, timeout=15.0)))
        results: dict = {}

        def run(job):
            kind, oids, thunk = job
            try:
                return thunk()
            except (ConnectionLost, OSError):
                if kind not in ("local", "head"):
                    # Owner process is gone: its objects resolve through
                    # the head's FT view from now on (or lineage re-exec).
                    self._dead_owners.add(kind)
                    head_oids.update(oids)
                return {}

        if len(jobs) == 1:
            outs = [run(jobs[0])]
        else:
            outs = list(self._get_pool().map(run, jobs))
        for out in outs:
            for oid, entry in (out or {}).items():
                if entry.get("forgotten"):
                    # The owner dropped its handle but we still hold one:
                    # the head's directory is the fallback of record.
                    head_oids.add(oid)
                elif entry.get("nodes"):
                    results[oid] = entry
        return results

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        """Resolve every ref: local reads first, then one batched
        location poll per round for everything still missing — against
        the LOCAL owner table for self-owned refs (no RPC), each owner's
        directory for borrowed refs, the head only as FT fallback — with
        ready objects fetched concurrently (the reference resolves from
        owners the same way, ownership_based_object_directory.h). Errors
        raise in ref order — an error ref raises once every ref before
        it has resolved."""
        deadline = None if timeout is None else time.monotonic() + timeout
        hooks = self._block_hooks
        blocked = False
        _UNSET = object()
        slots = [_UNSET] * len(refs)
        pending: dict[str, list[int]] = {}
        owner_of = {r.id: getattr(r, "_owner", "") for r in refs}
        head_oids: set[str] = set()  # oids demoted to head resolution
        fetch_fails: dict[str, int] = {}
        round_idx = 0

        def ordered_raise():
            for v in slots:
                if v is _UNSET:
                    return
                if isinstance(v, _GetError):
                    raise v.exc

        def resolve_value(oid: str, i: int):
            boxed = self._read_local(oid)
            if boxed is not None:
                slots[i] = boxed[0]
                return True
            return False

        try:
            for i, r in enumerate(refs):
                try:
                    if not resolve_value(r.id, i):
                        pending.setdefault(r.id, []).append(i)
                except BaseException as e:  # noqa: BLE001 — ordered raise
                    slots[i] = _GetError(e)
            ordered_raise()
            while pending:
                if hooks is not None and not blocked:
                    hooks[0]()  # give our CPUs back while we block
                    blocked = True
                # Window the poll: the head rescans the requested oids on
                # every store event while blocked, so a 5k-ref get must
                # not make each scan 5k wide. Refs resolve roughly in
                # submission order; polling the first unresolved window
                # keeps scans O(64) and still batches.
                window = list(pending)[:64]
                locs = self._poll_locations(
                    window, owner_of, head_oids,
                    sweep_head=(round_idx % 4 == 3))
                round_idx += 1
                ready = [(oid, loc) for oid, loc in locs.items()
                         if oid in pending]
                if ready:
                    def fetch(oid, loc):
                        try:
                            return self._fetch_remote(oid, loc["nodes"])
                        except (ObjectLostError, ConnectionLost,
                                OSError) as e:
                            # Owner-table locations aren't liveness-
                            # filtered the way the head's are: a died
                            # node leaves stale entries. Purge and retry
                            # the poll (recovery/re-exec decides next);
                            # after repeated failures resolve through
                            # the head, whose view drops dead nodes.
                            self._owner_drop(
                                oid, [nid for nid, _a, _s in loc["nodes"]])
                            n = fetch_fails[oid] = fetch_fails.get(oid, 0) + 1
                            if n >= 3:
                                head_oids.add(oid)
                            if n >= 6:
                                return _GetError(e)
                            return _REFETCH
                        except BaseException as e:  # noqa: BLE001
                            return _GetError(e)

                    if len(ready) == 1:
                        values = [fetch(*ready[0])]
                    else:
                        values = list(self._get_pool().map(
                            lambda p: fetch(*p), ready))
                    for (oid, _), value in zip(ready, values):
                        if value is _REFETCH:
                            continue
                        for i in pending.pop(oid):
                            slots[i] = value
                for oid in window:
                    if oid in pending and oid not in locs:
                        self._maybe_recover(oid)
                        self._check_actor_alive(oid)
                ordered_raise()
                if pending and deadline is not None \
                        and time.monotonic() > deadline:
                    raise GetTimeoutError(
                        f"ray_tpu.get timed out on {len(pending)} ref(s)")
        finally:
            if blocked:
                hooks[1]()
        for r in refs:
            self._actor_tasks.pop(r.id, None)  # resolved; stop tracking
        # Values may have carried nested ObjectRefs: make sure the head
        # knows about our new holds before our caller can release the
        # containers they arrived in.
        with self._ref_lock:
            dirty = bool(self._dirty_add)
        if dirty:
            self.flush_refs()
        return slots

    def _get_pool(self):
        """Concurrent fetches for multi-ref gets. Separate from the chunk
        pool (a fetch SUBMITS chunk work there; sharing would deadlock at
        saturation)."""
        pool = getattr(self, "_fetch_pool", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                pool = getattr(self, "_fetch_pool", None)
                if pool is None:
                    pool = self._fetch_pool = ThreadPoolExecutor(
                        4, thread_name_prefix="get-fetch")
        return pool

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        owner_of = {r.id: getattr(r, "_owner", "") for r in refs}
        head_oids: set[str] = set()
        round_idx = 0
        while len(ready) < num_returns:
            for r in list(pending):
                if self.store.contains(r.id):
                    ready.append(r)
                    pending.remove(r)
            if len(ready) >= num_returns or not pending:
                break
            # Actor-death fail-fast, same contract as get(): a pending
            # actor-call ref whose actor is DEAD can never resolve — and
            # its stored error object may have died WITH the actor's
            # node (a preempted gang bundle vacated mid-call), so a
            # wait()-based poller (Tune's event loop, the trainer's
            # consume loop) would otherwise spin forever. The error
            # lands in the local store and the ref reports ready (this
            # pass or the caller's next poll); get() raises it.
            # Replay-on-restart rides along (the same _check_actor_alive
            # path get() uses). Throttled PER CLIENT: repeated
            # wait(timeout=0) polls collectively sweep at most every
            # quarter second — each check is a head RPC per distinct
            # actor — and runs only after the contains-check above found
            # unresolved refs.
            now = time.monotonic()
            if now - getattr(self, "_last_actor_check", 0.0) > 0.25:
                self._last_actor_check = now
                from ray_tpu.core.object_ref import ActorError

                seen_actors: set = set()
                for r in list(pending):
                    entry = self._actor_tasks.get(r.id)
                    if entry is None:
                        continue
                    aid = entry["actor_id"]
                    # Only actors whose registration this client has
                    # already seen: a ctor still forking has no head
                    # record yet, and the lookup would BLOCK wait()
                    # for the registration timeout (ctor failures
                    # surface through the record the agent writes).
                    with self._lock:
                        known = aid in self._actor_cache
                    if not known:
                        continue
                    # One head refresh per DISTINCT actor per round: a
                    # wait over a 500-call fan-out to one actor must
                    # not cost 500 get_actor RPCs every quarter second.
                    # The entry's oids are captured BEFORE the check:
                    # it pops every sibling of a multi-return call, so
                    # the error must be stored for ALL of them or the
                    # unchecked siblings would hang forever.
                    call_oids = list(entry.get("oids") or [r.id])
                    try:
                        self._check_actor_alive(
                            r.id, refresh=aid not in seen_actors)
                    except ActorError as e:
                        for oid in call_oids:
                            self.put_with_id(oid, e, is_error=True)
                    except Exception:
                        pass  # lookup hiccup: next round retries
                    seen_actors.add(aid)
                for r in list(pending):
                    if self.store.contains(r.id):
                        ready.append(r)
                        pending.remove(r)
                if len(ready) >= num_returns or not pending:
                    break
            # One batched, owner-routed poll per round (non-blocking):
            # self-owned refs cost zero RPCs; the 5 ms cadence below would
            # otherwise hammer the head with a locations call per ref.
            locs = self._poll_locations(
                [r.id for r in pending], owner_of, head_oids,
                sweep_head=(round_idx % 64 == 63), timeout=0)
            round_idx += 1
            for r in list(pending):
                loc = locs.get(r.id)
                if loc and loc.get("nodes"):
                    ready.append(r)
                    pending.remove(r)
                    if fetch_local:
                        self._prefetch(r.id, loc["nodes"],
                                       owner=owner_of.get(r.id))
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        return ready, pending

    def _prefetch(self, oid: str, locations: list,
                  owner: str | None = None) -> None:
        """``wait(fetch_local=True)`` semantics (reference: ready objects
        are pulled to the caller's node): replicate the raw bytes into the
        LOCAL store in the background at wait priority, so the eventual
        ``get`` is a local read. Best-effort — failures leave the remote
        copy authoritative."""
        if any(node_id == self.node_id for node_id, _a, _s in locations):
            return  # already local
        with self._lock:
            if oid in self._prefetching:
                return
            self._prefetching.add(oid)
            pool = getattr(self, "_prefetch_pool", None)
            if pool is None:
                from concurrent.futures import ThreadPoolExecutor

                # Separate from the chunk pool: a prefetch occupies a
                # slot WHILE it submits chunk work there — sharing one
                # executor would deadlock at saturation.
                pool = self._prefetch_pool = ThreadPoolExecutor(
                    2, thread_name_prefix="prefetch")

        def job():
            try:
                with self.pull_priority_override(self.PULL_WAIT):
                    for _node_id, address, _sp in locations:
                        got = self._pull_object(address, oid)
                        if got is None:
                            continue
                        meta, data = got
                        self.store.put(oid, [bytes(data)], meta)
                        # Secondary copy: the owner's directory spreads
                        # future pulls across it; the head's batched view
                        # keeps it as a spill/FT candidate. No owner on
                        # the ref -> head only (we must not claim
                        # ownership of a borrowed object).
                        if owner:
                            self._report_location(
                                oid, owner, meta[:1] == b"E", len(data))
                        with self._ref_lock:
                            self._loc_dirty.append(
                                (oid, self.node_id, meta[:1] == b"E",
                                 len(data), None, owner or "", None))
                            self._ref_cv.notify_all()
                        return
            except BaseException:  # noqa: BLE001 — best-effort
                pass
            finally:
                with self._lock:
                    self._prefetching.discard(oid)

        pool.submit(job)

    # -- internal KV -------------------------------------------------------

    def kv_put(self, key: str, value, overwrite: bool = True) -> bool:
        return self.head.call("kv_put", key, value, overwrite)

    def kv_get(self, key: str):
        return self.head.call("kv_get", key)

    def kv_del(self, key: str) -> bool:
        return self.head.call("kv_del", key)

    def kv_keys(self, prefix: str = "") -> list[str]:
        return self.head.call("kv_keys", prefix)

    # -- task plane --------------------------------------------------------

    def _strategy_info(self, options: dict) -> dict:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
            PlacementGroupSchedulingStrategy,
        )

        strategy = options.get("scheduling_strategy")
        info: dict[str, Any] = {
            "strategy": strategy if isinstance(strategy, str) else None,
            "pg_id": None,
            "bundle_index": -1,
            "node_affinity": None,
        }
        pg = options.get("placement_group")
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg = strategy.placement_group
            info["bundle_index"] = strategy.placement_group_bundle_index
        elif isinstance(strategy, NodeAffinitySchedulingStrategy):
            info["node_affinity"] = strategy.node_id
        if pg is not None:
            info["pg_id"] = getattr(pg, "id", pg)
            if "placement_group_bundle_index" in options:
                info["bundle_index"] = options["placement_group_bundle_index"]
        return info

    def _resolve_runtime_env(self, options: dict) -> dict | None:
        """Package a task/actor runtime_env (upload content-addressed zips
        to the head KV) and return the shippable resolved spec."""
        env = options.get("runtime_env")
        if not env:
            return None
        import json as _json

        from ray_tpu._private import runtime_env as rtenv

        memo_key = _json.dumps(env, sort_keys=True, default=str)
        resolved = self._rtenv_cache.get(memo_key)
        if resolved is None:
            resolved = rtenv.package(
                env,
                lambda k, v, ow: self.head.call("kv_put", k, v, ow),
            )
            self._rtenv_cache[memo_key] = resolved
        return resolved

    def _choose_node(self, demand, sinfo, task_id=None):
        if sinfo["pg_id"] is not None:
            return self.head.call(
                "pg_node_for_bundle", sinfo["pg_id"], sinfo["bundle_index"],
                60.0, timeout=75.0,
            )
        return self.head.call(
            "schedule", demand, caller_node=self.node_id,
            strategy=sinfo["strategy"], node_affinity=sinfo["node_affinity"],
            task_id=task_id,
        )

    def _submit_spec(self, spec: dict, *, allow_pending: bool = False):
        placed = self._choose_node(spec["demand"], spec["sinfo"],
                                   task_id=spec.get("task_id"))
        if placed is None:
            if not allow_pending:
                raise ValueError(
                    f"demand {spec['demand']} is infeasible on this cluster"
                )
            # Keep the task pending while the autoscaler adds capacity
            # (reference: infeasible tasks wait; the demand is already
            # recorded head-side by the failed schedule call).
            threading.Thread(
                target=self._retry_submit, args=(spec,), daemon=True
            ).start()
            return
        node_id, address = placed
        spec["assigned_node"] = node_id
        self._register_borrows(spec, node_id)
        try:
            self._node_client(address).call("submit_task", spec)
        except (ConnectionLost, OSError):
            self._end_borrows(spec)  # nothing will ever end them otherwise
            raise

    def _register_borrows(self, spec: dict, node_id: str) -> None:
        """Task args borrow their objects until the task ends — registered
        BEFORE dispatch so the caller may drop its handles immediately.
        Actor-method borrows carry the actor id so the head can end them
        when the actor dies with calls still queued."""
        if spec.get("borrowed"):
            self.head.call(
                "ref_task_begin", spec["task_id"], node_id, spec["borrowed"],
                spec.get("actor_id") if spec.get("method") else None,
            )
        self._drop_holds(spec)

    def _register_borrows_batch(self, specs: list, node_id: str) -> None:
        entries = [
            (s["task_id"], node_id, s["borrowed"],
             s.get("actor_id") if s.get("method") else None)
            for s in specs if s.get("borrowed")
        ]
        if entries:
            self.head.call("ref_task_begin_batch", entries)
        for s in specs:
            self._drop_holds(s)

    def _deliver_late_cancels(self, specs: list, address: str) -> None:
        """cancel() racing the asynchronous dispatch sees assigned_node
        None and sends no node RPC; now that these specs have a home,
        forward the flag (the agent's cancelled-set covers every
        queue/checkout window)."""
        for s in specs:
            if s.get("cancelled"):
                try:
                    self._node_client(address).call(
                        "cancel_task", s["task_id"], False)
                except (ConnectionLost, OSError):
                    pass

    def _drop_holds(self, spec: dict) -> None:
        """Release the submission-window holds on a task's borrowed args
        (safe once the head knows the borrows, or the task has failed)."""
        oids = self._submit_holds.pop(spec.get("task_id"), None)
        if oids:
            for oid in oids:
                self._deref(oid)

    def _fail_spec(self, spec: dict, err: Exception) -> None:
        spec["_handled"] = True
        self._drop_holds(spec)
        for oid in spec["oids"]:
            self._lineage.pop(oid, None)
            self.put_with_id(oid, err, is_error=True)

    # -- lease-pipelined submission ----------------------------------------

    @staticmethod
    def _leasable(spec: dict) -> bool:
        """Only default-strategy tasks with real demand take the
        prefer-local direct path; SPREAD/affinity/PG placement must
        consult the head every time, and zero-demand specs fit local
        admission unconditionally (they'd never spill — the head
        round-robins them instead)."""
        s = spec["sinfo"]
        return (s["pg_id"] is None and s["node_affinity"] is None
                and s["strategy"] is None and bool(spec["demand"]))

    def _submit_loop(self) -> None:
        import heapq

        while True:
            with self._submit_cv:
                limit = config.submit_batch_max
                while True:
                    now = time.monotonic()
                    # Re-inject due retries AT MOST one batch per loop
                    # pass: at 100k parked specs hitting max backoff,
                    # every spec comes due inside the same window, and
                    # draining them ALL here would put ~400 consecutive
                    # retry batches ahead of any fresh submission (a
                    # feasible probe task measured 40s queue latency
                    # behind the circulating backlog). Bounded, the
                    # remainder stays at the heap top — still due, so
                    # the next pass drains the next batch — and fresh
                    # work interleaves at batch granularity.
                    drained = 0
                    while (self._retry_heap
                           and self._retry_heap[0][0] <= now
                           and drained < limit):
                        self._submit_q.append(
                            heapq.heappop(self._retry_heap)[2])
                        drained += 1
                    if self._submit_q or self._closed:
                        break
                    wait = 0.5
                    if self._retry_heap:
                        wait = min(wait, self._retry_heap[0][0] - now)
                    self._submit_cv.wait(max(wait, 0.01))
                if self._closed and not self._submit_q:
                    # Anything still in the retry heap is shutdown()'s
                    # to snapshot-and-fail; don't dispatch it here.
                    return
                batch = []
                while self._submit_q and len(batch) < limit:
                    batch.append(self._submit_q.popleft())
                # Popped-but-not-dispatched specs count as in flight so
                # shutdown()'s drain cannot slip between the pop and the
                # dispatch and release the submit holds early.
                self._dispatching = len(batch)
            for spec in batch:
                spec.pop("_handled", None)
            try:
                self._dispatch_batch(batch)
            except BaseException as e:  # noqa: BLE001 — submitter must live
                # Fail only specs the dispatch never handed off anywhere:
                # earlier specs in the batch may already be RUNNING on a
                # node, and writing a TaskError over their oids would race
                # their real results.
                _metrics.count_loop_restart("client.submit")
                for spec in batch:
                    if spec.get("_handled"):
                        continue
                    try:
                        self._fail_spec(spec, TaskError(
                            spec.get("fname", "task"),
                            f"submission failed: {e!r}", repr(e)))
                    # Per-spec error-write guard inside the already-
                    # counted batch handler: ticking here too would
                    # inflate the series by the batch width on one
                    # transient outage.
                    except BaseException:  # analyze: ignore[DL002]
                        pass
            finally:
                with self._submit_cv:
                    self._dispatching = 0

    def _queue_retry(self, spec: dict, delay: float | None = None) -> None:
        """Park a temporarily unplaceable spec for ONE shared retry timer
        (not a thread per spec): due specs re-enter the submit queue and
        re-batch through the normal dispatch path.

        Per-spec exponential backoff (submit_retry_base_s doubling to
        submit_retry_max_s): at 100k parked specs a flat 0.25s timer
        re-batched the ENTIRE backlog through schedule_batch every tick
        (~400 head RPCs per 250ms of pure misses, forever); backoff
        decays a standing backlog to a trickle while the first few
        attempts still land fast when capacity appears quickly."""
        import heapq

        spec["_handled"] = True
        spec.setdefault("_pending_since", time.monotonic())
        if delay is None:
            delay = spec.get("_retry_delay", config.submit_retry_base_s)
            spec["_retry_delay"] = min(
                config.submit_retry_max_s, delay * 2.0)
        with self._submit_cv:
            if not self._closed:
                self._retry_seq += 1
                heapq.heappush(
                    self._retry_heap,
                    (time.monotonic() + delay, self._retry_seq, spec))
                self._submit_cv.notify()
                return
        # Shutdown in progress: nothing will ever drain the retry heap
        # again (shutdown's fail pass may already have run) — fail the
        # spec into its refs now so no get() is left blocking. Guarded:
        # the store may already be unreachable this late in shutdown, and
        # an escape here would mark the spec handled-but-unfailed.
        try:
            self._end_borrows(spec)
            self._fail_spec(spec, TaskError(
                spec.get("fname", "task"),
                "client shut down with the task still unscheduled",
                "shutdown",
            ))
        except Exception:
            pass

    def _park_pending(self, spec: dict) -> None:
        """No feasible node right now: bounded retry via the shared timer
        (the head has recorded the demand for the autoscaler), honoring
        cancellation and the pending-task timeout."""
        from ray_tpu.core.object_ref import TaskCancelledError

        if spec.get("cancelled"):
            self._end_borrows(spec)
            self._fail_spec(
                spec, TaskCancelledError(spec.get("fname", "task")))
            return
        aff = spec["sinfo"].get("node_affinity")
        if aff is not None:
            # Hard affinity to a node that is DRAINING/DEAD can never
            # place. Recovery of PLACED specs already falls back to
            # soft affinity when the pinned node dies (_maybe_recover);
            # a never-placed spec parked on the same loss deserves the
            # same fallback instead of a guaranteed pending-timeout —
            # the chaos soak's drain-exemption probe hits exactly this
            # window when the drain lands before first placement.
            # Cached snapshot: a batch of parked affinity specs costs at
            # most one `nodes` RPC per second on the dispatch thread,
            # not one full-table fetch per spec per retry round.
            nodes = self._nodes_snapshot()
            if nodes is not None:
                info = nodes.get(aff)
                if info is None or not info.get("Alive") or \
                        info.get("State") == "DRAINING":
                    spec["sinfo"]["node_affinity"] = None
        since = spec.setdefault("_pending_since", time.monotonic())
        timeout = config.pending_task_timeout_s
        if time.monotonic() - since > timeout:
            self._end_borrows(spec)
            self._fail_spec(spec, TaskError(
                spec.get("fname", "task"),
                f"demand {spec['demand']} unsatisfiable for {timeout}s",
                "infeasible",
            ))
            return
        self._queue_retry(spec)

    def _dispatch_batch(self, batch: list) -> None:
        from ray_tpu.core.object_ref import TaskCancelledError

        failpoints.hit("client.dispatch.before_push")
        head_specs: list[dict] = []
        local_specs: list[dict] = []
        for spec in batch:
            if spec.get("cancelled"):
                self._end_borrows(spec)
                self._fail_spec(
                    spec, TaskCancelledError(spec.get("fname", "task")))
                continue
            if spec["sinfo"]["pg_id"] is not None:
                # PG bundles block on readiness server-side: keep them on
                # the per-spec path (rare, latency-insensitive).
                try:
                    self._submit_spec(spec, allow_pending=True)
                    spec["_handled"] = True
                except TimeoutError:
                    # Not ready within the resolve window — the group is
                    # still reserving, or RESCHEDULING while the head
                    # migrates bundles off a lost node. Park with the
                    # shared backoff timer: tasks pinned to a migrating
                    # gang re-resolve when the reservation lands, they
                    # don't error (bounded by pending_task_timeout_s).
                    self._park_pending(spec)
                except (ConnectionLost, OSError) as e:
                    if getattr(e, "maybe_executed", False):
                        # The push itself died mid-call: resubmitting
                        # could fork the task into two executions.
                        self._fail_spec(spec, TaskError(
                            spec.get("fname", "task"), str(e), repr(e)))
                    else:
                        # Nothing reached the node — typically a bundle
                        # host that died before the head declared it
                        # (the resolution pointed at a corpse). Park:
                        # the head flips the group to RESCHEDULING on
                        # death detection and the retry re-resolves to
                        # the bundle's new home.
                        self._park_pending(spec)
                except ValueError as e:
                    self._fail_spec(spec, TaskError(
                        spec.get("fname", "task"), str(e), repr(e)))
                continue
            if self._leasable(spec):
                local_specs.append(spec)
            else:
                head_specs.append(spec)

        if local_specs:
            # Prefer-local without the head: push to this client's own
            # node agent, which admits only what fits its UNCOMMITTED
            # capacity. Borrows register BEFORE dispatch (a begin must
            # never lose the race against the worker's task-end); a
            # rejected spec is re-registered by the head path
            # (begin-replaces semantics).
            rejected: set = set()
            try:
                agent = self._agent_client()
                self._register_borrows_batch(local_specs, self.node_id)
                for s in local_specs:
                    s["assigned_node"] = self.node_id
                rejected = set(agent.call(
                    "submit_tasks_leased", local_specs))
            except (ConnectionLost, OSError, RuntimeError) as e:
                if getattr(e, "maybe_executed", False):
                    # The push itself died mid-call: the agent may have
                    # enqueued the batch. Resubmitting could fork a task
                    # into two executions — fail the refs instead.
                    for s in local_specs:
                        self._end_borrows(s)
                        self._fail_spec(s, TaskError(
                            s.get("fname", "task"),
                            f"local agent unreachable during submit: "
                            f"{e!r}", repr(e)))
                    local_specs = []
                else:
                    # Nothing reached the agent (connect refused, borrow
                    # registration failed, ...): the whole set spills to
                    # head scheduling, exactly like a full local node.
                    rejected = set(range(len(local_specs)))
            spilled = []
            for i, s in enumerate(local_specs):
                if i in rejected:
                    spilled.append(s)
                else:
                    s["_handled"] = True
            if spilled:
                # Decentralized spillback (ray_syncer.h consumer): place
                # on a peer straight from the local agent's GOSSIPED
                # load view — same leased admission there; only what no
                # peer admits falls through to the head. The spilled
                # flag tells the head to avoid the caller's node (its
                # heartbeat hasn't reflected the leased admissions that
                # caused the rejection yet).
                for s in self._spill_to_peers(spilled):
                    s["assigned_node"] = None
                    s["_spilled"] = True
                    head_specs.append(s)
            if local_specs and len(rejected) < len(local_specs):
                self._deliver_late_cancels(
                    [s for i, s in enumerate(local_specs)
                     if i not in rejected],
                    self._agent_address)

        if not head_specs:
            return
        reqs = [
            {"demand": s["demand"], "caller_node": self.node_id,
             "strategy": s["sinfo"]["strategy"],
             "node_affinity": s["sinfo"]["node_affinity"],
             "task_id": s.get("task_id"),
             "spilled": s.pop("_spilled", False)}
            for s in head_specs
        ]
        try:
            placements = self.head.call("schedule_batch", reqs)
        except (ConnectionLost, OSError) as e:
            for s in head_specs:
                self._end_borrows(s)
                self._fail_spec(s, TaskError(
                    s.get("fname", "task"),
                    f"head unreachable during submit: {e!r}", repr(e)))
            return
        by_node: dict[tuple, list[dict]] = {}
        for spec, placed in zip(head_specs, placements):
            if placed is None:
                self._park_pending(spec)
                continue
            node_id, address = placed
            spec["assigned_node"] = node_id
            # Placement succeeded: the unplaceable-backoff streak is
            # over. A later transient push failure re-parks at the base
            # delay, not this spec's stale max-backoff cadence.
            spec.pop("_retry_delay", None)
            by_node.setdefault((node_id, address), []).append(spec)
        for (node_id, address), specs in by_node.items():
            try:
                self._register_borrows_batch(specs, node_id)
                self._node_client(address).call("submit_tasks", specs)
                for s in specs:
                    s["_handled"] = True
                self._deliver_late_cancels(specs, address)
            except (ConnectionLost, OSError):
                # Leave any borrow registrations in place: they pin the
                # args through the retry window (the caller may have
                # dropped its handles already); the retried dispatch
                # re-registers (begin-replaces) or ends them on failure.
                for s in specs:
                    s["assigned_node"] = None
                    self._queue_retry(s)

    def _node_confirmed_dead(self, node_id: str) -> bool:
        """Whether the HEAD declares this node dead (or gone entirely).
        A maybe-executed push to a confirmed-dead peer cannot fork
        execution — the process is gone and its store with it — so the
        spec is safe to resubmit. This is the zero-goodput-loss path
        for planned scale-down and spot preemption: the drain marks the
        node DEAD before the provider terminate, so a spillback racing
        the termination (gossip views stay fresh for seconds) falls
        back to head scheduling instead of failing the task."""
        try:
            nodes = self.head.call("nodes", timeout=5.0)
        except (ConnectionLost, OSError):
            return False  # can't confirm: stay conservative
        for n in nodes:
            if n["NodeID"] == node_id:
                return not n["Alive"]
        return True  # deregistered entirely

    def _spill_to_peers(self, specs: list) -> list:
        """Try to place locally-rejected leasable specs on peers chosen
        from the local agent's gossiped cluster view (no head RPC).
        Returns the specs no peer admitted; everything else is handed
        off (leased push, same admission as the local path)."""
        try:
            view = self._agent_client().call("peer_view", timeout=5.0)
        except (ConnectionLost, OSError):
            return specs
        now = time.time()
        avail: dict[str, dict] = {}
        addr_of: dict[str, str] = {}
        for nid, e in (view or {}).items():
            if nid == self.node_id or not e.get("address"):
                continue
            # Staleness gate is generous (gossip cadence stretches with
            # cluster size): the peer's LEASED admission is the real
            # correctness check — stale availability just costs a
            # rejected push and a head fallback.
            if now - e.get("ts", 0.0) > 10.0:
                continue
            avail[nid] = dict(e.get("available") or {})
            addr_of[nid] = e["address"]
        if not avail:
            return specs
        by_peer: dict[str, list] = {}
        unplaced: list = []
        for s in specs:
            demand = s["demand"]
            best = None
            for nid, av in avail.items():
                if all(av.get(k, 0.0) >= v for k, v in demand.items()):
                    if best is None or av.get("CPU", 0.0) > \
                            avail[best].get("CPU", 0.0):
                        best = nid
            if best is None:
                unplaced.append(s)
                continue
            for k, v in demand.items():
                avail[best][k] = avail[best].get(k, 0.0) - v
            by_peer.setdefault(best, []).append(s)
        for nid, group in by_peer.items():
            address = addr_of[nid]
            try:
                self._register_borrows_batch(group, nid)
                for s in group:
                    s["assigned_node"] = nid
                rej = set(self._node_client(address).call(
                    "submit_tasks_leased", group))
            except (ConnectionLost, OSError, RuntimeError) as e:
                if getattr(e, "maybe_executed", False) \
                        and not self._node_confirmed_dead(nid):
                    # The push died mid-call: the peer may have enqueued
                    # the batch; resubmitting could fork execution.
                    for s in group:
                        self._end_borrows(s)
                        self._fail_spec(s, TaskError(
                            s.get("fname", "task"),
                            f"peer agent unreachable during spillback: "
                            f"{e!r}", repr(e)))
                    continue
                rej = set(range(len(group)))
            for i, s in enumerate(group):
                if i in rej:
                    s["assigned_node"] = None
                    unplaced.append(s)
                else:
                    s["_handled"] = True
            if len(rej) < len(group):
                self._deliver_late_cancels(
                    [s for i, s in enumerate(group) if i not in rej],
                    address)
        return unplaced

    def _retry_submit(self, spec: dict, timeout: float | None = None):
        from ray_tpu.core.object_ref import TaskCancelledError

        if timeout is None:
            timeout = config.pending_task_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            time.sleep(0.25)
            failpoints.hit("client.retry_submit.tick")
            if spec.get("cancelled"):
                self._drop_holds(spec)
                self._end_borrows(spec)
                err = TaskCancelledError(spec.get("fname", "task"))
                for oid in spec["oids"]:
                    self.put_with_id(oid, err, is_error=True)
                return
            placed = self._choose_node(spec["demand"], spec["sinfo"],
                                   task_id=spec.get("task_id"))
            if placed is not None:
                node_id, address = placed
                spec["assigned_node"] = node_id
                self._register_borrows(spec, node_id)
                try:
                    self._node_client(address).call("submit_task", spec)
                except (ConnectionLost, OSError):
                    self._end_borrows(spec)
                    continue
                if spec.get("cancelled"):
                    # cancel() saw assigned_node=None and sent no node RPC;
                    # now that the task has a home, deliver it there (the
                    # agent's cancelled-set covers every dispatch window).
                    try:
                        self._node_client(address).call(
                            "cancel_task", spec["task_id"], False)
                    except (ConnectionLost, OSError):
                        pass
                return
        self._drop_holds(spec)
        self._end_borrows(spec)  # no-op unless a leased attempt registered
        err = TaskError(
            spec.get("fname", "task"),
            f"demand {spec['demand']} unsatisfiable for {timeout}s",
            "infeasible",
        )
        for oid in spec["oids"]:
            self.put_with_id(oid, err, is_error=True)

    def submit_task(
        self,
        func: Callable,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_retries: int | None = None,
        retry_exceptions: bool | tuple = False,
        name: str = "",
        **options,
    ) -> list[ObjectRef]:
        if max_retries is None:
            max_retries = config.task_default_max_retries
        task_id = ids.new_task_id()
        # Streaming generators: one tracked oid (index 0 = first yield);
        # later indices are synthesized by the ObjectRefGenerator.
        n_oids = 1 if num_returns == "streaming" else num_returns
        oids = [ids.object_id_for(task_id, i) for i in range(n_oids)]
        refs = [self.make_ref(o) for o in oids]
        borrowed: list[str] = []
        args_blob = ser.dumps((args, kwargs), found_refs=borrowed)
        # Function table (reference: function export to the GCS function
        # table, _private/function_manager.py): the function serializes
        # ONCE per driver, lands in the cluster KV under its content
        # hash, and specs carry only the hash — workers cache the
        # deserialized function. Refs captured in the closure are borrows
        # of every task using the function.
        fn_hash, closure_refs = self._export_function(func)
        borrowed.extend(closure_refs)
        spec = {
            "task_id": task_id,
            "oids": oids,
            "owner_addr": self.owner_addr,
            "num_returns": num_returns,
            "fname": name or getattr(func, "__name__", "task"),
            "func_hash": fn_hash,
            "args": args_blob,
            "borrowed": borrowed,
            "demand": demand_of(options, is_actor=False),
            "sinfo": self._strategy_info(options),
            "pg_id": None,
            "bundle_index": -1,
            "retries_left": max_retries,
            "runtime_env": self._resolve_runtime_env(options),
        }
        from ray_tpu.core import attribution

        site = attribution.submit_site()
        if site:
            # Submit-time callsite: the worker attributes the task's
            # return objects to the .remote() line.
            spec["callsite"] = site
        spec["pg_id"] = spec["sinfo"]["pg_id"]
        spec["bundle_index"] = spec["sinfo"]["bundle_index"]
        from contextlib import nullcontext

        from ray_tpu.util import tracing

        # Submission span covers the client-side submit (enqueue); its
        # context rides the spec so the worker parents the execution span
        # under it (tracing_helper.py). Dispatch itself is asynchronous —
        # the submitter thread batches it with its neighbors.
        span_cm = (tracing.span(f"submit:{spec['fname']}",
                                {"task_id": task_id})
                   if tracing.is_enabled() else nullcontext())
        with span_cm as s:
            if s is not None:
                spec["trace_ctx"] = {
                    "trace_id": s["trace_id"], "span_id": s["span_id"],
                }
            for oid in oids:
                self._lineage[oid] = spec
            if borrowed:
                # Hold borrowed args until the head learns of the borrows
                # (dispatch is async; the caller may drop its handles the
                # moment we return).
                for oid in borrowed:
                    self._incref(oid)
                self._submit_holds[task_id] = list(borrowed)
            with self._submit_cv:
                self._submit_q.append(spec)
                self._submit_cv.notify()
        return refs

    def release_stream(self, task_id: str, from_index: int) -> None:
        """Drop an abandoned stream's unconsumed items (ObjectRefGenerator
        finalizer): cooperatively cancel a still-running producer —
        bypassing cancel()'s finished-task guard, which a stream with one
        yielded item always trips — then have the head free the tail,
        present and future (stream_release)."""
        spec = self._lineage.get(ids.object_id_for(task_id, 0))
        if spec is not None:
            spec["retries_left"] = 0
            spec["cancelled"] = True
            assigned = spec.get("assigned_node")
            if assigned is not None:
                try:
                    nodes = {n["NodeID"]: n
                             for n in self.head.call("nodes")}
                    node = nodes.get(assigned)
                    if node is not None and node["Alive"]:
                        self._node_client(node["Address"]).call(
                            "cancel_task", spec["task_id"], False)
                except (ConnectionLost, OSError):
                    pass
        try:
            self.head.call("stream_release", task_id, from_index)
        except (ConnectionLost, OSError):
            pass

    def _export_function(self, func) -> tuple[str, list]:
        """(function_table_key, closure_ref_ids); exports to the KV on
        first sight. Keys are namespaced per driver (``fn:<client_id>:
        <hash>``) and deleted when the driver closes, so closure-heavy
        drivers can't grow the head without bound — the reference's
        function table is likewise scoped and cleaned per job. The memo
        is weak-keyed so dynamically created lambdas don't accumulate;
        unhashable callables just re-export."""
        import hashlib

        cached = None
        try:
            cached = self._fn_exports.get(func)
        except TypeError:
            pass
        if cached is None:
            closure_refs: list[str] = []
            blob = ser.dumps(func, found_refs=closure_refs)
            key = (f"fn:{self.client_id}:"
                   f"{hashlib.sha1(blob).hexdigest()}")
            # overwrite=False: first writer wins; same key = same bytes.
            self.head.call("kv_put", key, blob, False)
            with self._ref_lock:
                self._fn_keys.add(key)
            cached = (key, closure_refs)
            try:
                self._fn_exports[func] = cached
            except TypeError:
                pass
        return cached

    def submit_cpp_task(
        self,
        fname: str,
        packed_args: bytes,
        *,
        worker_bin: str | None = None,
        num_cpus: float = 1.0,
        num_returns: int = 1,
    ) -> list[ObjectRef]:
        """Submit a task executed by a native C++ worker
        (``cross_language.cpp_function``). The spec carries no Python
        function blob — ``fname`` names a function registered in the
        worker binary (RAYTPU_FUNC), args ride as a restricted-pickle
        blob the native codec decodes (``_native/src/pyvalue.h``)."""
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        refs = [self.make_ref(o) for o in oids]
        spec = {
            "task_id": task_id,
            "oids": oids,
            "owner_addr": self.owner_addr,
            "num_returns": num_returns,
            "fname": fname,
            "lang": "cpp",
            "cpp_args": packed_args,
            "cpp_worker_bin": worker_bin,
            "borrowed": [],
            "demand": {"CPU": float(num_cpus)},
            "sinfo": self._strategy_info({}),
            "pg_id": None,
            "bundle_index": -1,
            "retries_left": config.task_default_max_retries,
            "runtime_env": None,
        }
        for oid in oids:
            self._lineage[oid] = spec  # cpp specs are self-contained: a
            # node death re-submits them like any lineage re-execution
        try:
            self._submit_spec(spec, allow_pending=True)
        except (ValueError, TimeoutError) as e:
            for oid in oids:
                self._lineage.pop(oid, None)
                self.put_with_id(oid, TaskError(fname, str(e), repr(e)),
                                 is_error=True)
        return refs

    # -- actor plane -------------------------------------------------------

    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        max_concurrency: int = 1,
        **options,
    ) -> str:
        actor_id = ids.new_actor_id()
        borrowed: list[str] = []
        args_blob = ser.dumps((args, kwargs), found_refs=borrowed)
        cls_blob = ser.dumps(cls, found_refs=borrowed)
        spec = {
            "actor_create": True,
            "actor_id": actor_id,
            "task_id": ids.new_task_id(),
            "oids": [],
            "class_name": cls.__name__,
            "name": name,
            "fname": f"{cls.__name__}.__init__",
            "func": cls_blob,
            "args": args_blob,
            "borrowed": borrowed,
            "demand": demand_of(options, is_actor=True),
            "sinfo": self._strategy_info(options),
            "retries_left": 0,
            "runtime_env": self._resolve_runtime_env(options),
            # >1 = threaded actor: methods run on a pool of this many
            # executor threads (reference threaded-actor semantics; call
            # ordering is relaxed).
            "max_concurrency": int(max_concurrency),
            # {group_name: n_threads}: named executor groups with their
            # own queues (reference concurrency groups) — calls routed
            # via ActorMethod.options(concurrency_group=...).
            "concurrency_groups": options.get("concurrency_groups"),
        }
        spec["pg_id"] = spec["sinfo"]["pg_id"]
        spec["bundle_index"] = spec["sinfo"]["bundle_index"]
        # The head keeps the creation spec so it can reconstruct the actor
        # on worker/node death (max_restarts budget; -1 = infinite).
        self.head.call(
            "create_actor_record", actor_id,
            options.get("max_restarts", 0),
            options.get("max_task_retries", 0),
            spec,
        )
        with self._lock:
            self._actor_creations[actor_id] = spec
        self._submit_spec(spec)  # raises if infeasible
        return actor_id

    def _recover_actor_creation(self, actor_id: str) -> bool:
        """The actor never registered and the node its creation was
        dispatched to is gone: resubmit the creation spec (driver-side
        lineage for actor ctors; duplicate-safe because the assigned
        node is dead — its queue died with it). Returns True if a
        resubmission happened."""
        with self._lock:
            spec = self._actor_creations.get(actor_id)
            if spec is None or spec.get("_recovering"):
                # Another thread is already recovering this creation:
                # report True so the caller re-enters its wait instead
                # of failing — a second concurrent resubmit would fork
                # the ctor into two incarnations.
                return spec is not None and bool(spec.get("_recovering"))
            spec["_recovering"] = True
        try:
            assigned = spec.get("assigned_node")
            if assigned is None:
                return False  # not dispatched yet: absence is slowness
            nodes = self._nodes_snapshot()
            if nodes is None:
                return False
            if nodes.get(assigned, {}).get("Alive"):
                return False  # creation still in flight on a live node
            spec["assigned_node"] = None
            spec["sinfo"]["node_affinity"] = None
            try:
                self._submit_spec(spec)
            except (ValueError, TimeoutError):
                return False
            return True
        finally:
            with self._lock:
                spec.pop("_recovering", None)

    def _wait_actor_alive(self, actor_id: str, timeout: float = 60.0) -> dict:
        """Block through a RESTARTING window until the actor is ALIVE (or
        raise if it ends up DEAD / never recovers)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                info = self._actor_info(actor_id, refresh=True)
            except ValueError:
                # Never registered: the creation itself may have died
                # with its node — resubmit through creation lineage.
                if self._recover_actor_creation(actor_id) and \
                        time.monotonic() < deadline:
                    continue
                raise
            if info["state"] == "ALIVE":
                return info
            if info["state"] == "DEAD":
                raise ActorError(
                    f"actor {actor_id} is dead: {info['death_cause']}"
                )
            if time.monotonic() > deadline:
                raise ActorError(
                    f"actor {actor_id} stuck in {info['state']} for {timeout}s"
                )
            time.sleep(0.05)

    def _actor_info(self, actor_id: str, refresh: bool = False) -> dict:
        with self._lock:
            info = self._actor_cache.get(actor_id)
        if info is None or refresh or info["state"] != "ALIVE":
            t = config.actor_register_timeout_s
            info = self.head.call("get_actor", actor_id, t, timeout=t * 1.5)
            if info is None:
                raise ValueError(f"no such actor: {actor_id}")
            with self._lock:
                self._actor_cache[actor_id] = info
                # Registered: the head owns restarts from here on; the
                # creation-lineage spec is spent.
                self._actor_creations.pop(actor_id, None)
        return info

    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        **_options,
    ) -> list[ObjectRef]:
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        refs = [self.make_ref(o) for o in oids]
        borrowed: list[str] = []
        args_blob = ser.dumps((args, kwargs), found_refs=borrowed)
        spec = {
            "task_id": task_id,
            "actor_id": actor_id,
            "method": method_name,
            "oids": oids,
            "owner_addr": self.owner_addr,
            "num_returns": num_returns,
            "args": args_blob,
            "borrowed": borrowed,
            "concurrency_group": _options.get("concurrency_group"),
        }
        from ray_tpu.core import attribution

        site = attribution.submit_site()
        if site:
            spec["callsite"] = site
        try:
            try:
                info = self._actor_info(actor_id)
            except ValueError:
                # Creation lost with its node before registering: the
                # creation-lineage resubmit (duplicate-safe — the
                # assigned node is dead) brings it up elsewhere.
                if not self._recover_actor_creation(actor_id):
                    raise
                info = self._wait_actor_alive(actor_id)
            if info["state"] != "ALIVE":
                info = self._wait_actor_alive(actor_id)
            # Push under a TIME budget, not an attempt count: under
            # chaos (node kills, partitions, drain migrations) several
            # consecutive targets can each be transiently unreachable,
            # and a fixed attempt count burns out in milliseconds while
            # the head's view is stale. Genuine permanent death still
            # fails fast — _wait_actor_alive raises the moment the head
            # settles the actor DEAD.
            detect_s = max(config.node_death_timeout_s,
                           10 * config.heartbeat_interval_s)
            push_deadline = time.monotonic() + max(
                60.0, 3 * detect_s + 30.0)
            pushed = False
            while time.monotonic() < push_deadline:
                self._register_borrows(spec, info["node_id"])
                try:
                    self._worker_client(info["address"]).call(
                        "push_actor_task", spec
                    )
                    pushed = True
                    break
                except (ConnectionLost, OSError) as e:
                    self._end_borrows(spec)
                    if getattr(e, "maybe_executed", False):
                        # The push was FULLY sent and only the reply was
                        # lost: the worker most likely has (or ran) the
                        # call — its task-id dup-suppression makes the
                        # immediate re-push safe, so probe right away.
                        time.sleep(0.1)
                        info = self._wait_actor_alive(actor_id)
                        continue
                    # Worker unreachable at connect: the head may still
                    # report the dead incarnation ALIVE at this address
                    # for up to the death-detection window. Wait for the
                    # head's view to MOVE (restarted incarnation or new
                    # address) before re-pushing; fall out periodically
                    # to re-probe the same address in case the loss was
                    # a transient blip (chaos partition healing).
                    prev_addr = info["address"]
                    prev_restarts = info.get("num_restarts", 0)
                    moved_deadline = min(
                        time.monotonic() + detect_s + 5.0, push_deadline)
                    while time.monotonic() < moved_deadline:
                        info = self._wait_actor_alive(actor_id)
                        if info["address"] != prev_addr or \
                                info.get("num_restarts",
                                         0) > prev_restarts:
                            break
                        time.sleep(0.25)
            if not pushed:
                raise ActorError(f"actor {actor_id}: push failed repeatedly")
            # ONE shared entry for all return oids: a restart must replay
            # the call once, not once per return value.
            entry = {
                "actor_id": actor_id,
                "spec": spec,
                "oids": oids,
                "incarnation": info.get("num_restarts", 0),
                "retries_left": info.get("max_task_retries", 0),
            }
            for oid in oids:
                self._actor_tasks[oid] = entry
        except ActorError as e:
            self._end_borrows(spec)
            for oid in oids:
                self.put_with_id(oid, e, is_error=True)
        return refs

    def _end_borrows(self, spec: dict) -> None:
        if spec.get("borrowed"):
            try:
                self.head.call("ref_task_end", spec["task_id"])
            except (ConnectionLost, OSError):
                pass

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        info = self._actor_info(actor_id, refresh=True)
        if info["state"] == "DEAD":
            return
        if no_restart:
            # Burn the restart budget so an in-flight reconstruction can't
            # resurrect it either.
            try:
                self.head.call(
                    "mark_actor_dead", actor_id, "killed via ray_tpu.kill",
                    False,
                )
            except (ConnectionLost, OSError):
                pass
        nodes = {n["NodeID"]: n for n in self.head.call("nodes")}
        node = nodes.get(info["node_id"])
        if node is None or not node["Alive"]:
            return
        try:
            self._node_client(node["Address"]).call(
                "kill_actor", actor_id, no_restart
            )
        except (ConnectionLost, OSError):
            pass

    def get_named_actor(self, name: str) -> str:
        info = self.head.call("get_named_actor", name)
        if info is None or info["state"] == "DEAD":
            raise ValueError(f"no actor named {name!r}")
        with self._lock:
            self._actor_cache[info["actor_id"]] = info
        return info["actor_id"]

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        """Best-effort cancel (``ray.cancel`` parity): queued tasks are
        dropped and their refs raise TaskCancelledError; running tasks are
        force-killed (worker process) or cooperatively interrupted; actor
        calls are cancelled in the actor's queue or interrupted in place
        (the actor itself survives — force never kills an actor)."""
        oid = ref.id
        entry = self._actor_tasks.get(oid)
        if entry is not None:
            spec = entry["spec"]
            entry["retries_left"] = 0  # a cancelled call must not replay
            try:
                info = self._actor_info(spec["actor_id"], refresh=True)
                if info.get("address"):
                    self._worker_client(info["address"]).call(
                        "cancel_task", spec["task_id"], force
                    )
            except (ConnectionLost, OSError, ActorError, KeyError):
                pass
            return
        spec = self._lineage.get(oid)
        if spec is None:
            return  # finished-and-dropped or not owned here: no-op
        # Already-finished outputs have locations (or a local copy):
        # cancel must stay a no-op AND must not burn the lineage budget
        # that protects the computed value against later node loss.
        try:
            if self.store.contains(oid):
                return
            loc = self.head.call("locations", oid)
            if loc and loc["nodes"]:
                return
        except (ConnectionLost, OSError):
            pass
        spec["retries_left"] = 0   # no lineage re-exec of a cancelled task
        spec["cancelled"] = True   # the pending-retry thread checks this
        assigned = spec.get("assigned_node")
        if assigned is None:
            return  # still unplaced: _retry_submit stores the error
        try:
            nodes = {n["NodeID"]: n for n in self.head.call("nodes")}
            node = nodes.get(assigned)
            if node is not None and node["Alive"]:
                self._node_client(node["Address"]).call(
                    "cancel_task", spec["task_id"], force
                )
        except (ConnectionLost, OSError):
            pass

    # -- placement groups --------------------------------------------------

    def create_placement_group(self, bundles, strategy, name="",
                               lifetime=None, spot=True):
        # Client-generated id makes the call idempotent under the head
        # client's reconnect-window retry (a replayed create after a head
        # restart must not reserve a second PG's resources).
        pg_id = ids.new_placement_group_id()
        return self.head.call(
            "create_placement_group", bundles, strategy, name, lifetime,
            pg_id, spot,
        )

    def remove_placement_group(self, pg_id: str) -> None:
        self.head.call("remove_placement_group", pg_id)

    def placement_group_table(self, pg_id=None):
        table = self.head.call("placement_group_table", pg_id)
        if table is None:
            return None
        if pg_id is not None:
            return {**table, "state": table["state"]}
        return table

    def placement_group_ready(self, pg_id: str) -> ObjectRef:
        oid = ids.new_object_id()
        ref = self.make_ref(oid)

        def waiter():
            deadline = time.monotonic() + 300.0
            while time.monotonic() < deadline:
                table = self.head.call("placement_group_table", pg_id)
                if table is None:
                    break
                if table["state"] == "CREATED":
                    self.put_with_id(oid, pg_id)
                    return
                if table["state"] in ("INFEASIBLE", "REMOVED", "DEAD"):
                    break
                time.sleep(0.02)
            self.put_with_id(
                oid,
                ValueError(f"placement group {pg_id} cannot become ready"),
                is_error=True,
            )

        threading.Thread(target=waiter, daemon=True).start()
        return ref

    def current_placement_group(self):
        return None  # capture is a local-backend feature for now

    # -- introspection / lifecycle ----------------------------------------

    # -- state API (experimental/state/api.py analog) ----------------------

    def list_tasks(self, limit: int = 1000) -> list:
        return self.head.call("list_tasks", limit, timeout=15.0)

    def list_actors(self) -> list:
        return self.head.call("list_actors")

    def list_objects(self, limit: int = 1000) -> dict:
        """{"objects": [...], "truncated": bool, "total": int} — records
        sorted by size descending, enriched with owner/callsite/age."""
        return self.head.call("list_objects", limit)

    def memory_summary(self, top_k: int = 20,
                       group_by: str = "callsite") -> dict:
        """Cluster-wide object/memory rollup: totals + per-node shm
        occupancy + top-K objects + bytes grouped by callsite/task/node
        (``ray memory`` summary analog)."""
        return self.head.call("memory_summary", top_k, group_by,
                              timeout=30.0)

    def memory_leaks(self) -> list:
        """Objects the head's leak sweeper currently flags (alive past
        the age threshold with no reachable refs, or held refs whose
        every replica is gone)."""
        return self.head.call("memory_leaks", timeout=15.0)

    def object_store_stats(self, node_id=None,
                           include_objects: bool = True) -> list:
        """Per-node shm store stats, with the per-key size/refcount/
        pinned/attribution join when ``include_objects``."""
        return self.head.call("object_store_stats", node_id,
                              include_objects, timeout=30.0)

    # -- chaos / fault-injection control plane ------------------------------

    def set_failpoints(self, specs: dict,
                       include_workers: bool = True) -> dict:
        """Arm/disarm named failpoints cluster-wide (head -> agents ->
        workers). ``{site: spec}``; falsy spec disarms the site."""
        return self.head.call("set_failpoints", specs, include_workers,
                              timeout=30.0)

    def list_failpoints(self) -> dict:
        return self.head.call("list_failpoints", timeout=30.0)

    def set_channel_chaos(self, rules: list, label: str = "") -> dict:
        """Arm network-chaos rules (delay/drop/duplicate/sever) on the
        RPC plane of every cluster process."""
        return self.head.call("set_channel_chaos", rules, label,
                              timeout=30.0)

    def clear_channel_chaos(self, label=None) -> dict:
        return self.head.call("clear_channel_chaos", label, timeout=30.0)

    def partition(self, groups: list) -> dict:
        """Symmetric network partition between endpoint groups (lists of
        node ids, or the string "head"). Heal with ``heal()``."""
        return self.head.call("partition", groups, timeout=30.0)

    def heal(self) -> dict:
        return self.head.call("heal", timeout=30.0)

    # -- node reporter surface (logs / stacks / telemetry) -----------------

    def list_logs(self) -> list:
        """Per-worker captured log files across the cluster."""
        return self.head.call("list_logs", timeout=15.0)

    def get_log(self, worker_id: str, stream: str = "out",
                offset=None, max_bytes: int = 1 << 20,
                tail_lines=None, node_id=None) -> dict:
        return self.head.call(
            "get_log", worker_id, stream, offset, max_bytes, tail_lines,
            node_id, timeout=20.0)

    def follow_log(self, worker_id: str, stream: str = "out",
                   offset: int = 0, idle_timeout_s: float = 10.0,
                   node_id=None):
        """Iterator of {"offset", "data"} chunks — streamed end-to-end
        (agent file -> head proxy -> here) over the RPC plane."""
        return self.head.call_stream(
            "follow_log", worker_id, stream, offset, idle_timeout_s,
            node_id, timeout=idle_timeout_s + 60.0)

    def dump_worker_stack(self, worker_id: str, node_id=None) -> str:
        return self.head.call(
            "dump_worker_stack", worker_id, node_id, timeout=30.0)

    def profile_worker(self, worker_id: str, duration_s: float = 1.0,
                       interval_s: float = 0.01, node_id=None) -> dict:
        return self.head.call(
            "profile_worker", worker_id, duration_s, interval_s, node_id,
            timeout=float(duration_s) + 60.0)

    def worker_stats(self, fresh: bool = False) -> list:
        return self.head.call("worker_stats", fresh, timeout=15.0)

    def device_stats(self, fresh: bool = False) -> list:
        """Per-worker JAX/XLA device snapshots across the cluster."""
        return self.head.call("device_stats", fresh, timeout=20.0)

    def capture_profile(self, worker_id: str, duration_s: float = 1.0,
                        interval_s: float = 0.01, out_dir=None,
                        node_id=None) -> dict:
        """Remote profiler capture: jax.profiler.trace in the worker
        (stack-sampler fallback), trace files streamed back in bounded
        chunks through the log-read plane and written under ``out_dir``
        (a fresh temp dir by default)."""
        import tempfile

        from ray_tpu.util.device_telemetry import resolve_capture_path

        manifest = self.head.call(
            "capture_profile", worker_id, float(duration_s),
            float(interval_s), node_id,
            timeout=float(duration_s) + 120.0)
        out_dir = out_dir or tempfile.mkdtemp(prefix="ray_tpu_tprof_")
        paths = []
        for f in manifest.get("files", []):
            path = resolve_capture_path(out_dir, f["name"])
            if path is None:
                continue  # never let a remote name escape out_dir
            offset = 0
            with open(path, "wb") as fh:
                while True:
                    chunk = self.head.call(
                        "read_capture_file", manifest["node_id"],
                        manifest["capture_id"], f["name"], offset,
                        1 << 20, timeout=60.0)
                    data = chunk.get("data") or b""
                    if data:
                        fh.write(data)
                        offset = chunk["offset"]
                    if not data or offset >= chunk.get("size", 0):
                        break
            if offset < f.get("size", 0):
                # The agent served less than the manifest promised
                # (capture evicted mid-download): a partial trace is
                # corrupt, not a smaller one — fail the whole capture.
                raise ValueError(
                    f"capture file {f['name']!r} truncated at "
                    f"{offset}/{f['size']} bytes (capture evicted?)")
            paths.append(path)
        return {
            "kind": manifest.get("kind"),
            "worker_id": worker_id,
            "node_id": manifest.get("node_id"),
            "duration_s": manifest.get("duration_s"),
            "dir": out_dir,
            "files": paths,
        }

    def list_spans(self, trace_id=None, limit: int = 10_000) -> list:
        """Finished tracing spans from the head's span store (fed by the
        workers' batched event reports)."""
        return self.head.call("list_spans", trace_id, limit, timeout=15.0)

    # -- trace flight recorder (head-assembled; cluster/traces.py) ---------

    def _flush_spans_quiet(self):
        """Best-effort pre-query flush so a trace queried right after
        its request finished isn't missing this process's spans."""
        try:
            self._flush_spans()
        except Exception:
            pass

    def get_trace(self, trace_id: str):
        self._flush_spans_quiet()
        return self.head.call("get_trace", trace_id, timeout=15.0)

    def list_traces(self, limit: int = 50) -> list:
        self._flush_spans_quiet()
        return self.head.call("list_traces", limit, timeout=15.0)

    def trace_stats(self) -> dict:
        self._flush_spans_quiet()
        return self.head.call("trace_stats", timeout=15.0)

    def ttft_decomposition(self, window_s: float | None = None,
                           deployment: str | None = None) -> dict:
        self._flush_spans_quiet()
        return self.head.call("ttft_decomposition", window_s, deployment,
                              timeout=15.0)

    def cluster_metrics_text(self) -> str:
        """The head's federated /metrics/cluster body."""
        return self.head.call("cluster_metrics_text", timeout=30.0)

    def metrics_endpoint(self):
        """The head's scrape endpoint {address, cluster_path,
        targets_path}, or None when disabled."""
        return self.head.call("metrics_endpoint")

    # -- signal plane (head metrics history + SLOs) ------------------------

    def query_metrics(self, spec: dict) -> dict:
        """Windowed query against the head's history ring — zero sleeps
        anywhere in the path (pure ring read on the head)."""
        return self.head.call("query_metrics", spec, timeout=15.0)

    def slo_status(self) -> dict:
        return self.head.call("slo_status", timeout=15.0)

    def register_slo(self, name: str, expr: str) -> dict:
        """Register a declarative SLO, e.g.
        ``ttft_p50{deployment="d"} < 2s over 60s``."""
        return self.head.call("register_slo", name, expr, timeout=15.0)

    def remove_slo(self, name: str) -> dict:
        return self.head.call("remove_slo", name, timeout=15.0)

    def signal_top(self, window_s: float = 60.0) -> dict:
        """The ``ray-tpu top`` cluster rollup, all from history."""
        return self.head.call("signal_top", window_s, timeout=15.0)

    def autoscaler_status(self) -> dict:
        """The fleet autoscaler's last state report (per-type node
        counts, quarantine/backoff benches, draining nodes, active SLO
        burns); ``{}`` before the first reconcile pass."""
        return self.head.call("autoscaler_status", timeout=15.0)

    def _log_poll_loop(self, subscribed: bool = False) -> None:
        """Driver-side log streaming over the pubsub LOGS channel
        (long-poll push, ``src/ray/pubsub`` analog — replaces the old
        0.3s drain_logs polling; the drain RPC remains for CLI catch-up).
        A None poll result means the head lost our subscription (restart):
        re-subscribe and continue."""
        sub_id = "logs:" + self.client_id
        while not self._closed:
            try:
                if not subscribed:
                    self.head.call("pubsub_subscribe", sub_id, "LOGS")
                    subscribed = True
                got = self.head.call(
                    "pubsub_poll", sub_id, 10.0, timeout=15.0)
            except Exception:
                _metrics.count_loop_restart("client.log_poll")
                subscribed = False
                time.sleep(0.5)
                continue
            if got is None:
                subscribed = False  # head restarted: state was in-memory
                continue
            msgs, _dropped = got
            try:
                for m in msgs:
                    d = m["data"]
                    for line in d["lines"]:
                        print(
                            f"(pid={d['pid']}, node={d['node_id'][-8:]}) "
                            f"{line}"
                        )
            except Exception:
                # sys.stdout may be swapped/closed under us (pytest
                # capture) — drop this batch but NEVER kill the poller;
                # stdout usually comes back.
                _metrics.count_loop_restart("client.log_poll")
                continue

    def cluster_resources(self) -> dict:
        return self.head.call("cluster_resources")

    def available_resources(self) -> dict:
        return self.head.call("available_resources")

    def nodes(self) -> list[dict]:
        return self.head.call("nodes")

    def shutdown(self) -> None:
        """Disconnect this client (the cluster keeps running; use
        Cluster.shutdown / shutdown_cluster to tear it down)."""
        # This process's daemon loops die with it: retract their
        # restart series so the scrape doesn't carry dead children.
        _metrics.retract_loop_series(
            ["client.ref_flush", "client.submit", "client.log_poll"])
        # Drain the submit queue first: tasks handed to submit_task before
        # shutdown must reach a node (or fail into their refs) — then the
        # closed flag stops the submitter thread. "_dispatching" covers
        # the window where the submitter has popped a batch but not yet
        # registered its borrows.
        deadline = time.monotonic() + 5.0
        while ((self._submit_q or self._dispatching)
               and time.monotonic() < deadline):
            time.sleep(0.02)
        # Specs parked on the retry timer (unplaceable demand, node-submit
        # retries) can never run now — fail them into their result refs so
        # a concurrent get() raises instead of blocking to its own timeout.
        # _closed is set under the same lock BEFORE the heap snapshot so a
        # retry that comes due mid-shutdown cannot re-park after the clear
        # (_queue_retry fails specs instead once closed).
        with self._submit_cv:
            self._closed = True
            parked = [entry[2] for entry in self._retry_heap]
            self._retry_heap.clear()
            self._submit_cv.notify_all()
        for spec in parked:
            # Parked specs carry _handled=True (the dispatch loop took
            # responsibility) but by definition have produced no result.
            try:
                self._end_borrows(spec)
                self._fail_spec(spec, TaskError(
                    spec.get("fname", "task"),
                    "client shut down with the task still unscheduled",
                    "shutdown",
                ))
            except Exception:
                pass  # store may already be unreachable
        # Release every hold this process still has so the cluster can
        # free the objects (clean-exit ref release).
        with self._ref_lock:
            self._closed = True
            release = set(self._local_refs) | self._dirty_remove
            self._local_refs.clear()
            self._dirty_add.clear()
            self._dirty_remove.clear()
            self._ref_cv.notify_all()
        if release:
            try:
                self.head.call(
                    "ref_update", self.client_id, [], sorted(release)
                )
            except (ConnectionLost, OSError):
                pass
        # Function-table cleanup: this driver's exports are namespaced by
        # client_id, so deleting them can't break other drivers.
        with self._ref_lock:
            fn_keys, self._fn_keys = self._fn_keys, set()
        for key in fn_keys:
            try:
                self.head.call("kv_del", key)
            except (ConnectionLost, OSError):
                break  # head gone: its KV dies with it anyway
        with self._lock:
            clients = (
                list(self._node_clients.values())
                + list(self._worker_clients.values())
                + list(self._owner_clients.values())
            )
            self._node_clients.clear()
            self._worker_clients.clear()
            self._owner_clients.clear()
        for c in clients:
            c.close()
        # Owner directory dies with the owner (reference semantics: owner
        # failure = its objects become unrecoverable except via the head's
        # FT view / lineage). Borrowers fail over on ConnectionLost.
        try:
            self._owner_server.stop()
        except Exception:
            pass
        for attr in ("_chunk_pool", "_prefetch_pool", "_fetch_pool"):
            pool = getattr(self, attr, None)
            if pool is not None:
                pool.shutdown(wait=False)
        if self.process_kind == "d":
            # Only drivers subscribe; workers have nothing to clean up.
            try:
                self.head.call(
                    "pubsub_unsubscribe", "logs:" + self.client_id)
            except (ConnectionLost, OSError):
                pass  # publisher TTL evicts the subscription anyway
        self._pins.clear()
        self.store.close()
        self.head.close()


def connect(address: str, **kwargs):
    """Backend factory for ``ray_tpu.init(address=...)``.

    ``host:port`` — direct driver on a cluster machine (shared-memory
    object plane). ``ray://host:port`` — remote client through a
    ClientProxyServer (no shm needed; reference Ray Client semantics).
    """
    if address.startswith("ray://"):
        from ray_tpu.util.client import ClientBackend

        return ClientBackend(address.removeprefix("ray://"))
    return ClusterBackend(address.removeprefix("tcp://"))
