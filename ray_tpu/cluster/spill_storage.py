"""Pluggable spill storage: where cold object bytes go under pressure.

Reference parity: ``python/ray/_private/external_storage.py`` — the
reference routes spilled objects through an ``ExternalStorage`` chosen
by config (filesystem / smart_open URI); here the ``spill_uri`` config
knob picks a registered backend by URI scheme.

Two deployment shapes:

* **node-local** (default, ``spill_uri=""``): each agent spills into its
  per-session ``/tmp/ray_tpu_spill_*`` directory. Fast, zero setup — but
  a dead node takes its spilled objects with it (recovery falls back to
  lineage recomputation).
* **remote** (``spill_uri="file:///shared/dir"`` or any registered
  scheme): every agent spills into one shared target keyed by object id.
  The head records each spilled object, and when a node dies its spilled
  objects are *restored from the URI onto a live node* by lineage
  recovery instead of being recomputed or lost
  (``node_agent.rpc_restore_from_uri`` / ``head.rpc_restore_spilled``).

The on-target layout is one file per object id:
``8-byte little-endian meta length + meta + data`` — identical to the
historic local spill-file format, so the chunked fetch fallback can
range-read the data section without loading the object.

Register new schemes (s3/gcs/...) with :func:`register_scheme`; the
factory receives the full URI and returns a :class:`SpillBackend`.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Optional, Tuple


class SpillBackend:
    """One spill target. ``remote`` declares whether the target survives
    the writing node's death (drives head spill-record reporting and the
    restore-from-URI recovery path)."""

    remote = False
    uri = ""

    def write(self, oid: str, meta: bytes, data: bytes) -> int:
        """Persist one object; returns total bytes written. Must be
        atomic per object (a reader never sees a torn file)."""
        raise NotImplementedError

    def read(self, oid: str) -> Optional[Tuple[bytes, bytes]]:
        """(meta, data) or None when the target has no such object."""
        raise NotImplementedError

    def read_range(self, oid: str, offset: int,
                   length: int) -> Optional[bytes]:
        """One bounded slice of the DATA section (chunked fetch
        fallback), or None when absent."""
        raise NotImplementedError

    def delete(self, oid: str) -> bool:
        """Drop the object from the target (free-on-zero broadcast);
        returns whether it existed."""
        raise NotImplementedError

    def stats(self) -> dict:
        """{"objects": n, "bytes": n} currently on the target."""
        raise NotImplementedError


class FileSpillBackend(SpillBackend):
    """Filesystem spill target (``file://`` scheme and the node-local
    default). A shared filesystem (NFS, gcsfuse) mounted at the same
    path on every node makes this a remote backend."""

    def __init__(self, root: str, *, remote: bool = False, uri: str = ""):
        self.root = root
        self.remote = remote
        self.uri = uri or f"file://{root}"
        os.makedirs(root, exist_ok=True)

    def _path(self, oid: str) -> str:
        return os.path.join(self.root, oid)

    def write(self, oid: str, meta: bytes, data: bytes) -> int:
        path = self._path(oid)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(len(meta).to_bytes(8, "little"))
            f.write(meta)
            f.write(data)
        os.replace(tmp, path)
        return 8 + len(meta) + len(data)

    def read(self, oid: str) -> Optional[Tuple[bytes, bytes]]:
        try:
            with open(self._path(oid), "rb") as f:
                meta_len = int.from_bytes(f.read(8), "little")
                meta = f.read(meta_len)
                data = f.read()
        except OSError:
            return None
        return meta, data

    def read_range(self, oid: str, offset: int,
                   length: int) -> Optional[bytes]:
        try:
            with open(self._path(oid), "rb") as f:
                meta_len = int.from_bytes(f.read(8), "little")
                f.seek(8 + meta_len + offset)
                return f.read(length)
        except OSError:
            return None

    def delete(self, oid: str) -> bool:
        try:
            os.unlink(self._path(oid))
            return True
        except OSError:
            return False

    def stats(self) -> dict:
        objects = 0
        nbytes = 0
        try:
            for name in os.listdir(self.root):
                if ".tmp." in name:
                    continue  # in-flight writes aren't spilled objects
                try:
                    nbytes += os.path.getsize(
                        os.path.join(self.root, name))
                    objects += 1
                except OSError:
                    continue  # deleted under us
        except OSError:
            pass
        return {"objects": objects, "bytes": nbytes}


def _file_factory(uri: str) -> SpillBackend:
    path = uri[len("file://"):]
    if not path.startswith("/"):
        raise ValueError(
            f"spill_uri {uri!r}: file:// target must be an absolute "
            f"path (file:///shared/dir)")
    return FileSpillBackend(path, remote=True, uri=uri)


# scheme -> factory(uri) -> SpillBackend. file:// ships; object stores
# register here (the smart_open dispatch of the reference collapsed to
# an explicit table).
_SCHEMES: Dict[str, Callable[[str], SpillBackend]] = {
    "file": _file_factory,
}
_schemes_lock = threading.Lock()


def register_scheme(scheme: str,
                    factory: Callable[[str], SpillBackend]) -> None:
    """Plug a spill backend for ``<scheme>://`` URIs (s3, gcs, ...)."""
    with _schemes_lock:
        _SCHEMES[scheme] = factory


def registered_schemes() -> list:
    with _schemes_lock:
        return sorted(_SCHEMES)


def backend_for(uri: str) -> SpillBackend:
    """The backend behind a spill URI. Raises ``ValueError`` on an
    unknown scheme so a typo'd ``spill_uri`` fails at agent boot, not at
    the first spill under memory pressure."""
    scheme, sep, _rest = uri.partition("://")
    if not sep or not scheme:
        raise ValueError(
            f"spill_uri {uri!r} is not a <scheme>://... URI; known "
            f"schemes: {registered_schemes()}")
    with _schemes_lock:
        factory = _SCHEMES.get(scheme)
    if factory is None:
        raise ValueError(
            f"spill_uri scheme {scheme!r} has no registered backend; "
            f"known: {registered_schemes()} "
            f"(spill_storage.register_scheme to add one)")
    return factory(uri)


def local_backend(spill_dir: str) -> FileSpillBackend:
    """The per-node session spill dir as a (non-remote) backend."""
    return FileSpillBackend(spill_dir, remote=False)
