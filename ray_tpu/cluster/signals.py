"""Cluster signal plane: metrics history ring, windowed queries, SLOs.

The sensing half of the autoscaler (ROADMAP items 1 and 4 consume the
query API built here). Every metric family in the system is a lifetime
total; the only windowed view used to be ``serve.stats(window_s)``
sleeping between two scrapes — banned from the dashboard path since PR
8 because a sleep in a request path stalls every pane. This module
gives the head a memory instead:

* **MetricsRing** — the head's scrape loop feeds each federated
  ``/metrics/cluster`` body through the one parser
  (``util/metrics.parse_prometheus``) into per-series deques of
  ``(ts, value)``. Retention is bounded twice over (PR-6 discipline):
  samples age out past ``signal_history_s`` AND each deque has a hard
  ``maxlen``; distinct series are capped at ``signal_max_series`` with
  least-recently-updated eviction. Dead nodes' series are aged out on
  the death edge (``Head._mark_dead``), stale series a history window
  after they stop reporting; every eviction is counted into
  ``ray_tpu_head_signal_evictions_total{reason}`` — never a silent cap.

* **windowed queries** — ``rate`` / ``delta`` / ``gauge_avg`` /
  ``gauge_max`` / ``gauge_last`` / ``trend`` over counters and gauges,
  and ``quantile_over_window`` over histograms computed from bucket
  deltas between ring snapshots (same interpolation as
  ``quantile_from_buckets`` — one quantile definition everywhere).
  Zero sleeps by construction: a query only ever reads history.

* **SLO layer** — declarative objects (``ttft_p50{deployment="d"} <
  2s over 60s``, ``shed_ratio < 1% over 300s``, ``rate(
  ray_tpu_oom_kills_total) < 1 over 300s``) evaluated by a head loop
  into burn-rate state ok -> warning -> burning with hysteresis
  (``slo_burn_evals`` consecutive breaching evaluations to burn, the
  same count of clean ones to recover; a scrape gap evaluates to None
  and HOLDS state — the evaluator must not flap on missing data).
  Transitions to/from burning publish structured events on the pubsub
  ``SLO`` channel (drain/OOM event shape) and the current state is
  exported as ``ray_tpu_slo_*`` gauges on the same scrape the ring
  ingests.
"""

from __future__ import annotations

import collections
import re
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _metrics
from ray_tpu.util.metrics import (
    _labels_get,
    parse_prometheus,
    quantile_from_buckets,
)

SLO_STATES = ("ok", "warning", "burning")
_STATE_CODE = {"ok": 0.0, "warning": 1.0, "burning": 2.0}

# Signal shorthands the SLO grammar resolves (the serve/train planes'
# SLO-able signals by their operator-facing names; anything else uses
# the generic op(metric) form).
_NAMED_SIGNALS: Dict[str, tuple] = {
    "ttft_p50": ("quantile", "ray_tpu_serve_decode_ttft_seconds",
                 0.50, {}),
    "ttft_p99": ("quantile", "ray_tpu_serve_decode_ttft_seconds",
                 0.99, {}),
    "itl_p50": ("quantile", "ray_tpu_serve_decode_itl_seconds",
                0.50, {}),
    "itl_p99": ("quantile", "ray_tpu_serve_decode_itl_seconds",
                0.99, {}),
    "latency_p50": ("quantile", "ray_tpu_serve_request_seconds",
                    0.50, {"phase": "total"}),
    "latency_p99": ("quantile", "ray_tpu_serve_request_seconds",
                    0.99, {"phase": "total"}),
    "qps": ("rate", "ray_tpu_serve_requests_total", None, {}),
    "shed_ratio": ("ratio", "ray_tpu_serve_shed_total",
                   "ray_tpu_serve_requests_total", {}),
    "error_ratio": ("ratio_match", "ray_tpu_serve_requests_total",
                    "ray_tpu_serve_requests_total",
                    {"status": "error"}),
    "queue_depth": ("gauge_avg", "ray_tpu_serve_router_queue_depth",
                    None, {}),
    "queue_depth_trend": ("trend", "ray_tpu_serve_router_queue_depth",
                          None, {}),
    # Step anatomy plane (round 19). mfu averages across rank series
    # (summing ranks would report a 2-rank gang at 40% as 80%);
    # step_p99 is the classic per-report step residual; sync_ratio is
    # the sync phase's share of the per-rank anatomy gauges — the
    # "gang is waiting, not computing" burn signal.
    "mfu": ("gauge_mean", "ray_tpu_mfu_percent", None, {}),
    "step_p99": ("quantile", "ray_tpu_train_step_phase_seconds",
                 0.99, {"phase": "step"}),
    "sync_ratio": ("gauge_ratio", "ray_tpu_step_phase_seconds",
                   {"phase": "sync"}, {}),
}

_GENERIC_OPS = ("rate", "delta", "gauge_avg", "gauge_max", "gauge_last",
                "gauge_mean", "trend", "p50", "p90", "p95", "p99")

_SLO_RE = re.compile(
    r"^\s*(?P<sig>[a-zA-Z_][a-zA-Z0-9_]*)"
    r"(?:\(\s*(?P<arg>[a-zA-Z_:][a-zA-Z0-9_:]*)\s*\))?"
    r"\s*(?:\{(?P<labels>[^}]*)\})?"
    r"\s*(?P<op><=|>=|<|>)\s*"
    r"(?P<val>\d+(?:\.\d+)?)\s*(?P<unit>ms|s|%)?"
    r"(?:\s+over\s+(?P<win>\d+(?:\.\d+)?)\s*s?)?\s*$")
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"?([^",]*)"?')


def parse_slo(expr: str) -> dict:
    """SLO grammar -> spec dict. Examples::

        ttft_p50{deployment="d"} < 2s over 60s
        shed_ratio < 1% over 300s
        p99(ray_tpu_task_phase_seconds) < 0.5s over 120s
        rate(ray_tpu_oom_kills_total) < 1 over 300s
        queue_depth_trend < 5 over 120s

    Raises ``ValueError`` on anything the grammar doesn't cover — a
    typo'd SLO must fail at registration, not evaluate to None forever.
    """
    m = _SLO_RE.match(expr or "")
    if not m:
        raise ValueError(f"unparseable SLO expression {expr!r}")
    sig, arg = m.group("sig"), m.group("arg")
    match = {k: v for k, v in
             _LABEL_PAIR_RE.findall(m.group("labels") or "")}
    threshold = float(m.group("val"))
    unit = m.group("unit")
    window_s = float(m.group("win") or 60.0)
    if arg is not None:
        if sig not in _GENERIC_OPS:
            raise ValueError(
                f"unknown signal op {sig!r} (have {_GENERIC_OPS})")
        if sig.startswith("p") and sig[1:].isdigit():
            signal = ("quantile", arg, int(sig[1:]) / 100.0, {})
        else:
            signal = (sig, arg, None, {})
    else:
        named = _NAMED_SIGNALS.get(sig)
        if named is None:
            raise ValueError(
                f"unknown named signal {sig!r} "
                f"(have {sorted(_NAMED_SIGNALS)})")
        signal = named
    # Unit scaling AFTER signal resolution: a family measured in
    # percent (``..._percent``) takes `< 40%` literally as 40, not
    # 0.4 — `mfu{trial="x"} < 40% over 120s` must mean what it says.
    if unit == "ms":
        threshold /= 1e3
    elif unit == "%":
        if not str(signal[1]).endswith("_percent"):
            threshold /= 100.0
    return {
        "expr": expr.strip(),
        "signal": signal,
        "match": match,
        "op": m.group("op"),
        "threshold": threshold,
        "window_s": window_s,
    }


def _compare(value: float, op: str, threshold: float) -> bool:
    """True when the SLO HOLDS."""
    if op == "<":
        return value < threshold
    if op == "<=":
        return value <= threshold
    if op == ">":
        return value > threshold
    return value >= threshold


class _Slo:
    __slots__ = ("name", "spec", "state", "breach_streak", "ok_streak",
                 "last_value", "last_eval_ts", "missed_evals",
                 "transitions")

    def __init__(self, name: str, spec: dict):
        self.name = name
        self.spec = spec
        self.state = "ok"
        self.breach_streak = 0
        self.ok_streak = 0
        self.last_value: Optional[float] = None
        self.last_eval_ts: Optional[float] = None
        self.missed_evals = 0
        self.transitions = 0

    def status(self) -> dict:
        return {
            "name": self.name,
            "expr": self.spec["expr"],
            "state": self.state,
            "value": self.last_value,
            "threshold": self.spec["threshold"],
            "op": self.spec["op"],
            "window_s": self.spec["window_s"],
            "breach_streak": self.breach_streak,
            "missed_evals": self.missed_evals,
            "transitions": self.transitions,
            "last_eval_ts": self.last_eval_ts,
        }


class MetricsRing:
    """Bounded per-series time-series history over parsed expositions.

    Series key = ``(metric_name, sorted label tuple)`` — exactly the
    parser's shape, so ingest is one dict walk. All mutation happens
    under one lock; queries snapshot under the same lock (the scrape
    cadence is seconds, series counts are thousands — contention is
    not a concern at this scale, and a torn read would be)."""

    def __init__(self, history_s: float = 600.0,
                 max_series: int = 50_000,
                 scrape_interval_s: float = 2.0):
        self.history_s = max(1.0, float(history_s))
        self.max_series = max(16, int(max_series))
        # Hard per-series bound: the retention window's worth of
        # samples at the configured cadence, plus slack for jitter.
        self._maxlen = max(
            8, int(self.history_s / max(0.05, scrape_interval_s)) + 8)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, tuple], collections.deque] = {}
        self._last_seen: Dict[Tuple[str, tuple], float] = {}
        self._snap_ts: collections.deque = collections.deque(
            maxlen=self._maxlen)
        self.evictions = {"series_cap": 0, "dead_node": 0, "stale": 0}

    # -- ingest ------------------------------------------------------------

    def ingest_text(self, ts: float, text: str) -> int:
        return self.ingest(ts, parse_prometheus(text))

    def ingest(self, ts: float, parsed: dict) -> int:
        """One scrape snapshot into the ring; returns the live series
        count after ingest (the self-overhead gauge's value)."""
        cutoff = ts - self.history_s
        with self._lock:
            self._snap_ts.append(ts)
            for name, series in parsed.items():
                for labels, value in series.items():
                    key = (name, labels)
                    dq = self._series.get(key)
                    if dq is None:
                        dq = collections.deque(maxlen=self._maxlen)
                        self._series[key] = dq
                    dq.append((ts, value))
                    self._last_seen[key] = ts
            # Age out: old samples everywhere, then whole series that
            # stopped reporting a full history window ago (a removed
            # deployment, a retracted gauge child).
            stale = []
            for key, dq in self._series.items():
                while dq and dq[0][0] < cutoff:
                    dq.popleft()
                if not dq or self._last_seen.get(key, 0.0) < cutoff:
                    stale.append(key)
            for key in stale:
                self._drop_locked(key, "stale")
            # Series cap, enforced ONCE per snapshot (a per-insert LRU
            # scan is O(series) per eviction — quadratic under a churn
            # storm, and this runs on the head): one sort, drop the
            # least-recently-updated excess. A single snapshot may
            # overshoot transiently inside this lock; it never returns
            # over cap.
            if len(self._series) > self.max_series:
                excess = len(self._series) - self.max_series
                doomed = sorted(
                    self._series,
                    key=lambda k: self._last_seen.get(k, 0.0))[:excess]
                for key in doomed:
                    self._drop_locked(key, "series_cap")
            return len(self._series)

    def _drop_locked(self, key, reason: str) -> None:
        self._series.pop(key, None)
        self._last_seen.pop(key, None)
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        try:
            _metrics.HEAD_SIGNAL_EVICTIONS_TOTAL.inc(
                tags={"reason": reason})
        except Exception:
            pass

    def age_out_node(self, node_id: str) -> int:
        """Drop every series labelled with a dead node (called on the
        node-death edge so queries never average a corpse in)."""
        with self._lock:
            doomed = [key for key in self._series
                      if _labels_get(key[1], "node_id") == node_id]
            for key in doomed:
                self._drop_locked(key, "dead_node")
            return len(doomed)

    # -- introspection -----------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def latest_ts(self) -> Optional[float]:
        with self._lock:
            return self._snap_ts[-1] if self._snap_ts else None

    def window_span(self, window_s: float) -> float:
        """The actual elapsed seconds the ring can answer for a
        requested window (ring younger than the window answers what it
        has; < 2 snapshots answers 0)."""
        with self._lock:
            if len(self._snap_ts) < 2:
                return 0.0
            latest = self._snap_ts[-1]
            start = latest - float(window_s)
            inside = [t for t in self._snap_ts if t >= start]
            if len(inside) < 2:
                return 0.0
            return inside[-1] - inside[0]

    def _matched(self, name: str, start: float,
                 match: Optional[dict]) -> List[Tuple[tuple, list]]:
        """[(labels, [(ts, v) in window])] for one family, filtered by
        exact label matches, under the lock."""
        out = []
        match = match or {}
        with self._lock:
            for (nm, labels), dq in self._series.items():
                if nm != name:
                    continue
                if any(_labels_get(labels, k) != v
                       for k, v in match.items()):
                    continue
                samples = [s for s in dq if s[0] >= start]
                if samples:
                    out.append((labels, samples))
        return out

    # -- windowed queries --------------------------------------------------

    def _anchor(self, window_s: float) -> Tuple[float, float]:
        latest = self.latest_ts()
        if latest is None:
            return 0.0, 0.0
        return latest, latest - max(0.0, float(window_s))

    def counter_delta(self, name: str, window_s: float,
                      match: Optional[dict] = None,
                      group_by: Optional[str] = None):
        """Sum of per-series increases inside the window (negative
        per-series deltas clamp to 0 — a restarted process's counter
        reset is not negative traffic). Returns ``(value_or_groups,
        elapsed_s)``."""
        _, start = self._anchor(window_s)
        groups: Dict[str, float] = {}
        elapsed = 0.0
        for labels, samples in self._matched(name, start, match):
            delta = max(0.0, samples[-1][1] - samples[0][1])
            span = samples[-1][0] - samples[0][0]
            elapsed = max(elapsed, span)
            key = (_labels_get(labels, group_by) or "") if group_by \
                else ""
            groups[key] = groups.get(key, 0.0) + delta
        if group_by:
            return groups, elapsed
        return groups.get("", 0.0), elapsed

    def rate(self, name: str, window_s: float,
             match: Optional[dict] = None,
             group_by: Optional[str] = None):
        """Per-second increase over the window; (value, elapsed_s)."""
        value, elapsed = self.counter_delta(
            name, window_s, match, group_by)
        if elapsed <= 0:
            return (({} if group_by else None), 0.0)
        if group_by:
            return ({k: v / elapsed for k, v in value.items()},
                    elapsed)
        return value / elapsed, elapsed

    def gauge_over_window(self, name: str, window_s: float,
                          agg: str = "avg",
                          match: Optional[dict] = None,
                          group_by: Optional[str] = None):
        """avg/max/last of a gauge family's samples in the window,
        summed across matched series per group (per-node CPU is the sum
        of its workers' gauges; per-deployment queue depth the sum of
        its routers')."""
        _, start = self._anchor(window_s)
        # group -> ts -> summed value across series
        per_ts: Dict[str, Dict[float, float]] = {}
        for labels, samples in self._matched(name, start, match):
            key = (_labels_get(labels, group_by) or "") if group_by \
                else ""
            bucket = per_ts.setdefault(key, {})
            for ts, v in samples:
                bucket[ts] = bucket.get(ts, 0.0) + v
        out: Dict[str, float] = {}
        for key, bucket in per_ts.items():
            vals = [bucket[t] for t in sorted(bucket)]
            if agg == "max":
                out[key] = max(vals)
            elif agg == "last":
                out[key] = vals[-1]
            else:
                out[key] = sum(vals) / len(vals)
        if group_by:
            return out
        return out.get("")

    def gauge_mean_over_window(self, name: str, window_s: float,
                               match: Optional[dict] = None,
                               group_by: Optional[str] = None):
        """Mean ACROSS matched series of each series' window average.
        ``gauge_over_window`` sums series (per-node CPU semantics);
        utilization families like MFU need the mean — summing would
        report a 2-rank gang at 40% each as 80%."""
        _, start = self._anchor(window_s)
        per_group: Dict[str, List[float]] = {}
        for labels, samples in self._matched(name, start, match):
            key = (_labels_get(labels, group_by) or "") if group_by \
                else ""
            vals = [v for _, v in samples]
            per_group.setdefault(key, []).append(
                sum(vals) / len(vals))
        out = {k: sum(v) / len(v) for k, v in per_group.items()}
        if group_by:
            return out
        return out.get("")

    def trend(self, name: str, window_s: float,
              match: Optional[dict] = None) -> Optional[float]:
        """Per-second growth of a gauge over the window: (second-half
        mean - first-half mean) / (window/2). Positive = climbing."""
        latest, start = self._anchor(window_s)
        if latest <= 0:
            return None
        mid = (latest + start) / 2.0
        per_ts: Dict[float, float] = {}
        for _labels, samples in self._matched(name, start, match):
            for ts, v in samples:
                per_ts[ts] = per_ts.get(ts, 0.0) + v
        first = [v for t, v in per_ts.items() if t < mid]
        second = [v for t, v in per_ts.items() if t >= mid]
        if not first or not second:
            return None
        half = max(1e-9, (latest - start) / 2.0)
        return (sum(second) / len(second)
                - sum(first) / len(first)) / half

    def quantile_over_window(self, name: str, q: float, window_s: float,
                             match: Optional[dict] = None
                             ) -> Optional[dict]:
        """PromQL-style windowed quantile from bucket deltas between
        ring snapshots: per-bucket-series increase inside the window,
        summed across matched series (cumulative counts stay cumulative
        under per-le subtraction). Returns {"value", "count", "sum",
        "resolution_s", "window_s"} or None when no samples moved."""
        _, start = self._anchor(window_s)
        buckets: Dict[float, float] = {}
        elapsed = 0.0
        for labels, samples in self._matched(
                name + "_bucket", start, match):
            le_raw = _labels_get(labels, "le")
            if le_raw is None:
                continue
            le = float("inf") if le_raw == "+Inf" else float(le_raw)
            delta = max(0.0, samples[-1][1] - samples[0][1])
            buckets[le] = buckets.get(le, 0.0) + delta
            elapsed = max(elapsed, samples[-1][0] - samples[0][0])
        count, _ = self.counter_delta(name + "_count", window_s, match)
        total, _ = self.counter_delta(name + "_sum", window_s, match)
        if not buckets or count <= 0:
            return None
        dist = {"buckets": sorted(buckets.items()), "sum": total,
                "count": count}
        value = quantile_from_buckets(dist, q)
        if value is None:
            return None
        from ray_tpu.util.metrics import bucket_width_at

        return {
            "value": value,
            "count": count,
            "sum": total,
            "resolution_s": bucket_width_at(dist, value),
            "window_s": elapsed,
        }

    def series_deltas(self, name: str, window_s: float,
                      match: Optional[dict] = None):
        """Per-series increase in window as wire-friendly
        ``[[label pairs, delta], ...]`` plus the elapsed span (the
        ``serve.stats`` history path consumes this shape)."""
        _, start = self._anchor(window_s)
        out = []
        for labels, samples in self._matched(name, start, match):
            out.append([[list(kv) for kv in labels],
                        max(0.0, samples[-1][1] - samples[0][1])])
        return out, self.window_span(window_s)


class SignalPlane:
    """MetricsRing + SLO registry + query dispatch (the head owns one;
    everything it exposes is also reachable in-process for tests and
    the bench)."""

    def __init__(self, history_s: float = 600.0,
                 max_series: int = 50_000,
                 scrape_interval_s: float = 2.0,
                 burn_evals: int = 3):
        self.ring = MetricsRing(history_s, max_series, scrape_interval_s)
        self.burn_evals = max(1, int(burn_evals))
        self._slo_lock = threading.Lock()
        self._slos: Dict[str, _Slo] = {}
        # Metrics<->trace exemplars: the trace store's lookup hook
        # (deployment, min_duration_s, limit) -> [{"trace_id", ...}].
        # Optional — a plane without a trace store answers without
        # exemplars, it never fails an SLO surface over them.
        self._exemplar_source = None

    def set_exemplar_source(self, source) -> None:
        self._exemplar_source = source

    def _exemplars_for(self, slo: _Slo, limit: int = 3) -> List[dict]:
        """Sampled trace_ids for the traffic this SLO watches: latency-
        quantile SLOs ask for traces at/over the threshold (the ones IN
        the breaching histogram buckets), everything else takes the
        slowest recent traces for the deployment."""
        if self._exemplar_source is None:
            return []
        kind = slo.spec["signal"][0]
        match = {**slo.spec["signal"][3], **slo.spec["match"]}
        min_s = slo.spec["threshold"] if kind == "quantile" else 0.0
        try:
            return list(self._exemplar_source(
                deployment=match.get("deployment"),
                min_duration_s=min_s, limit=limit) or [])
        except Exception:
            return []

    # -- ingest (head scrape loop) ----------------------------------------

    def ingest_text(self, ts: float, text: str) -> int:
        return self.ring.ingest_text(ts, text)

    def age_out_node(self, node_id: str) -> int:
        return self.ring.age_out_node(node_id)

    def series_count(self) -> int:
        return self.ring.series_count()

    # -- query dispatch (rpc_query_metrics) --------------------------------

    def query(self, spec: dict) -> dict:
        """One windowed query. ``spec``: {"op", "name", "window_s",
        "q"?, "match"?, "group_by"?}. Returns {"ok": bool, ...} — never
        raises on an unknown family (empty ring answers are a normal
        cold-start state the caller handles)."""
        if not isinstance(spec, dict):
            return {"ok": False, "error": "spec must be a dict"}
        op = spec.get("op")
        name = spec.get("name", "")
        window_s = float(spec.get("window_s", 60.0) or 60.0)
        match = spec.get("match") or {}
        group_by = spec.get("group_by")
        try:
            if op == "rate":
                value, elapsed = self.ring.rate(
                    name, window_s, match, group_by)
                return {"ok": True, "op": op, "name": name,
                        "value": value, "window_s": elapsed}
            if op == "delta":
                value, elapsed = self.ring.counter_delta(
                    name, window_s, match, group_by)
                return {"ok": True, "op": op, "name": name,
                        "value": value, "window_s": elapsed}
            if op in ("gauge_avg", "gauge_max", "gauge_last"):
                value = self.ring.gauge_over_window(
                    name, window_s, op[len("gauge_"):], match, group_by)
                return {"ok": True, "op": op, "name": name,
                        "value": value,
                        "window_s": self.ring.window_span(window_s)}
            if op == "gauge_mean":
                value = self.ring.gauge_mean_over_window(
                    name, window_s, match, group_by)
                return {"ok": True, "op": op, "name": name,
                        "value": value,
                        "window_s": self.ring.window_span(window_s)}
            if op == "trend":
                value = self.ring.trend(name, window_s, match)
                return {"ok": True, "op": op, "name": name,
                        "value": value,
                        "window_s": self.ring.window_span(window_s)}
            if op == "quantile":
                q = float(spec.get("q", 0.5))
                res = self.ring.quantile_over_window(
                    name, q, window_s, match)
                if res is None:
                    return {"ok": True, "op": op, "name": name,
                            "q": q, "value": None, "window_s": 0.0}
                return {"ok": True, "op": op, "name": name, "q": q,
                        **res}
            if op == "series_delta":
                series, elapsed = self.ring.series_deltas(
                    name, window_s, match)
                return {"ok": True, "op": op, "name": name,
                        "series": series, "window_s": elapsed}
            return {"ok": False,
                    "error": f"unknown query op {op!r}"}
        except Exception as e:  # a malformed spec answers, not raises
            return {"ok": False, "error": repr(e)}

    # -- SLO registry ------------------------------------------------------

    def register_slo(self, name: str, expr: str) -> dict:
        """Parse + register (idempotent per name: re-registering
        replaces the spec and resets the burn state)."""
        spec = parse_slo(expr)
        slo = _Slo(name, spec)
        with self._slo_lock:
            self._slos[name] = slo
        try:
            _metrics.SLO_THRESHOLD.set(spec["threshold"],
                                       tags={"slo": name})
            _metrics.SLO_STATE.set(0.0, tags={"slo": name})
        except Exception:
            pass
        return slo.status()

    def remove_slo(self, name: str) -> bool:
        with self._slo_lock:
            existed = self._slos.pop(name, None) is not None
        # Retract the per-SLO gauge children so a removed objective
        # vanishes from the federated scrape (LC001 discipline).
        try:
            _metrics.SLO_STATE.remove(tags={"slo": name})
            _metrics.SLO_VALUE.remove(tags={"slo": name})
            _metrics.SLO_THRESHOLD.remove(tags={"slo": name})
        except Exception:
            pass
        return existed

    def slo_status(self) -> dict:
        with self._slo_lock:
            slos = {name: (slo.status(), slo)
                    for name, slo in self._slos.items()}
        out = {}
        for name, (status, slo) in slos.items():
            if status["state"] in ("burning", "warning"):
                status["exemplar_trace_ids"] = [
                    e["trace_id"] for e in self._exemplars_for(slo)]
            out[name] = status
        return {"slos": out, "burn_evals": self.burn_evals,
                "series": self.ring.series_count(),
                "evictions": dict(self.ring.evictions)}

    def _signal_value(self, slo: _Slo) -> Optional[float]:
        kind, a, b, base_match = slo.spec["signal"]
        match = {**base_match, **slo.spec["match"]}
        window_s = slo.spec["window_s"]
        if kind == "quantile":
            res = self.ring.quantile_over_window(a, b, window_s, match)
            return None if res is None else res["value"]
        if kind == "rate":
            value, elapsed = self.ring.rate(a, window_s, match)
            return None if elapsed <= 0 else value
        if kind == "delta":
            value, elapsed = self.ring.counter_delta(a, window_s, match)
            return None if elapsed <= 0 else value
        if kind in ("gauge_avg", "gauge_max", "gauge_last"):
            return self.ring.gauge_over_window(
                a, window_s, kind[len("gauge_"):], match)
        if kind == "gauge_mean":
            return self.ring.gauge_mean_over_window(a, window_s, match)
        if kind == "trend":
            return self.ring.trend(a, window_s, match)
        if kind == "gauge_ratio":
            # sync_ratio shape: one phase's share of a gauge family —
            # numerator extra labels ride in `b` (a dict), denominator
            # is the same family with them stripped (all phases, all
            # ranks summed per snapshot), so the value is the gang-wide
            # share of step wall spent in that phase.
            num = self.ring.gauge_over_window(
                a, window_s, "avg", {**match, **b})
            den = self.ring.gauge_over_window(a, window_s, "avg", match)
            if num is None or den is None or den <= 0:
                return None
            return num / den
        if kind == "ratio":
            # shed_ratio shape: numerator family / denominator family,
            # the shared match filtering both (deployment=...).
            num, elapsed = self.ring.counter_delta(a, window_s, match)
            den, _ = self.ring.counter_delta(b, window_s, match)
            if elapsed <= 0:
                return None
            return num / den if den > 0 else 0.0
        if kind == "ratio_match":
            # error_ratio shape: same family, extra labels on the
            # numerator only.
            num, elapsed = self.ring.counter_delta(a, window_s, match)
            den_match = {k: v for k, v in match.items()
                         if k not in base_match}
            den, _ = self.ring.counter_delta(b, window_s, den_match)
            if elapsed <= 0:
                return None
            return num / den if den > 0 else 0.0
        return None

    def evaluate_slos(self, now: float) -> List[dict]:
        """One evaluator pass: update every SLO's burn state and gauges;
        return the transition events to publish (only the burning /
        recovered edges — warning wiggle stays on the gauge)."""
        events: List[dict] = []
        with self._slo_lock:
            slos = list(self._slos.values())
        for slo in slos:
            value = self._signal_value(slo)
            slo.last_eval_ts = now
            if value is None:
                # Scrape gap / cold ring: hold state, never flap.
                slo.missed_evals += 1
                continue
            slo.last_value = value
            holds = _compare(value, slo.spec["op"],
                             slo.spec["threshold"])
            prev = slo.state
            if holds:
                slo.breach_streak = 0
                slo.ok_streak += 1
                if slo.state == "warning":
                    slo.state = "ok"
                elif slo.state == "burning" \
                        and slo.ok_streak >= self.burn_evals:
                    slo.state = "ok"
            else:
                slo.ok_streak = 0
                slo.breach_streak += 1
                if slo.breach_streak >= self.burn_evals:
                    slo.state = "burning"
                elif slo.state == "ok":
                    slo.state = "warning"
            if slo.state != prev:
                slo.transitions += 1
            if (prev != "burning" and slo.state == "burning") or \
                    (prev == "burning" and slo.state == "ok"):
                ev = {
                    "slo": slo.name,
                    "expr": slo.spec["expr"],
                    "state": slo.state,
                    "prev": prev,
                    "value": value,
                    "threshold": slo.spec["threshold"],
                    "window_s": slo.spec["window_s"],
                    "ts": now,
                }
                if slo.state == "burning":
                    # A burn event names concrete traces: the operator
                    # goes straight from "it's burning" to `ray-tpu
                    # trace <id>` without hunting for a repro.
                    ev["exemplar_trace_ids"] = [
                        e["trace_id"] for e in self._exemplars_for(slo)]
                events.append(ev)
            try:
                _metrics.SLO_STATE.set(_STATE_CODE[slo.state],
                                       tags={"slo": slo.name})
                _metrics.SLO_VALUE.set(float(value),
                                       tags={"slo": slo.name})
                _metrics.SLO_THRESHOLD.set(
                    slo.spec["threshold"], tags={"slo": slo.name})
            except Exception:
                pass
        return events

    # -- the `ray-tpu top` rollup ------------------------------------------

    def top_summary(self, window_s: float = 60.0) -> dict:
        """One cluster view from history — per-node CPU/RSS/store
        occupancy, serve QPS/TTFT/shed burn, train goodput — with zero
        sleeps in the path (every number is a ring query)."""
        ring = self.ring
        nodes: Dict[str, dict] = {}
        cpu = ring.gauge_over_window(
            "ray_tpu_worker_cpu_percent", window_s, "avg",
            group_by="node_id") or {}
        rss = ring.gauge_over_window(
            "ray_tpu_worker_rss_bytes", window_s, "last",
            group_by="node_id") or {}
        used = ring.gauge_over_window(
            "ray_tpu_object_store_bytes_used", window_s, "last",
            group_by="node_id") or {}
        cap = ring.gauge_over_window(
            "ray_tpu_object_store_bytes_capacity", window_s, "last",
            group_by="node_id") or {}
        workers = ring.gauge_over_window(
            "ray_tpu_node_worker_count", window_s, "last",
            group_by="node_id") or {}
        for nid in set(cpu) | set(rss) | set(used) | set(workers):
            if not nid:
                continue
            entry = {"cpu_percent": round(cpu.get(nid, 0.0), 1),
                     "rss_bytes": int(rss.get(nid, 0.0)),
                     "workers": int(workers.get(nid, 0.0))}
            if cap.get(nid):
                entry["store_occupancy"] = round(
                    used.get(nid, 0.0) / cap[nid], 4)
            nodes[nid] = entry
        serve: Dict[str, dict] = {}
        qps, _ = self.ring.rate(
            "ray_tpu_serve_requests_total", window_s,
            group_by="deployment")
        shed, _ = self.ring.rate(
            "ray_tpu_serve_shed_total", window_s,
            group_by="deployment")
        for dep, dep_qps in (qps or {}).items():
            if not dep:
                continue
            entry = {"qps": round(dep_qps, 2)}
            total = dep_qps
            if total > 0:
                entry["shed_ratio"] = round(
                    (shed or {}).get(dep, 0.0) / total, 4)
            ttft = ring.quantile_over_window(
                "ray_tpu_serve_decode_ttft_seconds", 0.50, window_s,
                {"deployment": dep})
            if ttft is not None:
                entry["ttft_p50_s"] = round(ttft["value"], 4)
            itl = ring.quantile_over_window(
                "ray_tpu_serve_decode_itl_seconds", 0.50, window_s,
                {"deployment": dep})
            if itl is not None:
                entry["itl_p50_s"] = round(itl["value"], 5)
            lat = ring.quantile_over_window(
                "ray_tpu_serve_request_seconds", 0.50, window_s,
                {"deployment": dep, "phase": "total"})
            if lat is not None:
                entry["latency_p50_s"] = round(lat["value"], 4)
            serve[dep] = entry
        train: Dict[str, dict] = {}
        reports, elapsed = self.ring.rate(
            "ray_tpu_train_reports_total", window_s, group_by="trial")
        downtime, _ = self.ring.counter_delta(
            "ray_tpu_train_downtime_seconds_total", window_s,
            group_by="trial")
        for trial, rps in (reports or {}).items():
            if not trial:
                continue
            entry = {"reports_per_s": round(rps, 3)}
            down = (downtime or {}).get(trial, 0.0)
            if elapsed > 0:
                entry["goodput_pct"] = round(
                    max(0.0, 1.0 - down / elapsed) * 100.0, 1)
            if down:
                entry["downtime_s"] = round(down, 1)
            train[trial] = entry
        # Step anatomy: windowed MFU per trial plus the straggler
        # verdict from the per-rank phase gauges (the same attributor
        # train_stats uses, so top and stats can never disagree).
        from ray_tpu.util.goodput import (
            ANATOMY_PHASES,
            straggler_attribution,
        )

        mfu_by_trial = ring.gauge_mean_over_window(
            "ray_tpu_mfu_percent", window_s, group_by="trial") or {}
        anat_trials = set(ring.gauge_over_window(
            "ray_tpu_step_phase_seconds", window_s, "last",
            group_by="trial") or {})
        for trial in sorted(
                (set(mfu_by_trial) | anat_trials) - {""}):
            entry = train.setdefault(trial, {})
            if mfu_by_trial.get(trial) is not None:
                entry["mfu_pct"] = round(mfu_by_trial[trial], 2)
            rank_phases: Dict[str, Dict[str, float]] = {}
            for phase in ANATOMY_PHASES:
                per_rank = ring.gauge_over_window(
                    "ray_tpu_step_phase_seconds", window_s, "last",
                    {"trial": trial, "phase": phase},
                    group_by="rank") or {}
                for rank, val in per_rank.items():
                    rank_phases.setdefault(rank, {})[phase] = val
            verdict = straggler_attribution(rank_phases)
            if verdict:
                entry["straggler"] = verdict
        # Fleet churn: the autoscaler's counter families (windowed
        # deltas per node type) + the live pending-demand gauge — empty
        # until an autoscaler's registry lands in the ring.
        fleet_types: Dict[str, dict] = {}
        for key, fam in (
                ("launches", "ray_tpu_autoscaler_launches_total"),
                ("launch_failures",
                 "ray_tpu_autoscaler_launch_failures_total"),
                ("quarantines", "ray_tpu_autoscaler_quarantines_total"),
                ("scale_downs", "ray_tpu_autoscaler_scale_downs_total")):
            delta, _ = self.ring.counter_delta(
                fam, window_s, group_by="node_type")
            for t, v in (delta or {}).items():
                if not t or not v:
                    continue
                fleet_types.setdefault(t, {})[key] = int(v)
        pending = ring.gauge_over_window(
            "ray_tpu_autoscaler_pending_demand", window_s, "last",
            group_by="kind") or {}
        return {
            "window_s": window_s,
            "nodes": nodes,
            "serve": serve,
            "train": train,
            "fleet": {
                "types": fleet_types,
                "pending_demand": {k: int(v) for k, v in pending.items()
                                   if k and v},
            },
            "slos": self.slo_status()["slos"],
            "series": ring.series_count(),
            "evictions": dict(ring.evictions),
        }
