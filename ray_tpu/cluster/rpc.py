"""Minimal threaded RPC: length-prefixed pickle over TCP.

Plays the role of the reference's gRPC scaffolding (``src/ray/rpc/``):
request/response with per-connection FIFO ordering (the property the direct
actor transport relies on for in-order actor calls,
``direct_actor_task_submitter.h``). Handlers run on a thread per connection;
blocking handlers (long-poll style) are therefore fine.

Wire format: 4-byte big-endian length || pickled {"m": method, "a": args,
"k": kwargs} — responses {"ok": bool, "v": value} or {"ok": False,
"e": exception}.

Authentication: when a cluster token is configured (``RAY_TPU_CLUSTER_TOKEN``
/ ``config.cluster_token``), every server sends a random challenge on
accept and requires ``HMAC-SHA256(token, challenge)`` back before serving
— unauthenticated peers never reach the pickle deserializer. The hello
frame is sent either way so token/no-token peers fail fast instead of
deadlocking. Without a token (the default for localhost dev clusters)
behavior is unchanged.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import threading
import time
import traceback
from typing import Any, Callable

_LEN = struct.Struct(">I")


def get_cluster_token() -> bytes:
    from ray_tpu.core.config import config

    return config.cluster_token.encode()


class AuthError(Exception):
    """The peer failed (or refused) the cluster-token handshake."""


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


def _send_msg(sock: socket.socket, obj: Any) -> None:
    blob = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionLost("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


class RpcServer:
    """Serves ``rpc_<method>`` methods of a handler object."""

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0,
                 token: bytes | None = None):
        self._handler = handler
        self._token = get_cluster_token() if token is None else token
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stopped = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # Handler instrumentation (reference: the asio instrumented event
        # loop's per-handler stats, src/ray/common/asio event_stats.h):
        # per-method call count / cumulative / max seconds, cheap enough
        # to stay always-on.
        self._stats: dict[str, list] = {}  # method -> [count, total_s, max_s]
        self._stats_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _record_stat(self, method: str, dt: float) -> None:
        with self._stats_lock:
            ent = self._stats.get(method)
            if ent is None:
                self._stats[method] = [1, dt, dt]
            else:
                ent[0] += 1
                ent[1] += dt
                if dt > ent[2]:
                    ent[2] = dt

    def handler_stats(self) -> dict:
        """{method: {count, total_s, max_s, mean_ms}} snapshot."""
        with self._stats_lock:
            return {
                m: {
                    "count": c, "total_s": round(t, 6),
                    "max_s": round(mx, 6),
                    "mean_ms": round(1000.0 * t / c, 3) if c else 0.0,
                }
                for m, (c, t, mx) in self._stats.items()
            }

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._stopped.is_set():
                    # Raced stop(): it already swept the set — this conn
                    # must not outlive the server (head-restart correctness).
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _handshake_server(self, conn: socket.socket) -> bool:
        """Raw-byte MUTUAL hello/challenge exchange — runs BEFORE any
        pickle frame, so unauthenticated bytes never reach the
        deserializer. The server also proves token knowledge over the
        client's nonce, so a spoofed server (e.g. an attacker binding a
        dead head's port) cannot downgrade reconnecting peers."""
        challenge = os.urandom(32)
        required = b"\x01" if self._token else b"\x00"
        try:
            conn.sendall(b"RTPA1" + required + challenge)
            if not self._token:
                return True
            frame = _recv_exact(conn, 64)  # digest || client nonce
            digest, client_nonce = frame[:32], frame[32:]
            expect = hmac.new(
                self._token, challenge, hashlib.sha256).digest()
            ok = hmac.compare_digest(digest, expect)
            # The proof is bound to BOTH nonces and only sent to a client
            # that proved token knowledge first. Either property alone
            # stops the relay attack (a MITM forwarding our nonce to a
            # live server with a garbage digest to harvest a proof);
            # belt-and-braces we do both.
            if ok:
                proof = hmac.new(
                    self._token, challenge + client_nonce,
                    hashlib.sha256).digest()
            else:
                proof = bytes(32)
            conn.sendall((b"\x01" if ok else b"\x00") + proof)
            return ok
        except (ConnectionLost, OSError):
            return False

    def _serve_conn(self, conn: socket.socket):
        try:
            if not self._handshake_server(conn):
                return
            while True:
                req = _recv_msg(conn)
                t0 = time.perf_counter()
                try:
                    fn = getattr(self._handler, "rpc_" + req["m"])
                    value = fn(*req.get("a", ()), **req.get("k", {}))
                    self._record_stat(req["m"], time.perf_counter() - t0)
                    _send_msg(conn, {"ok": True, "v": value})
                except ConnectionLost:
                    raise
                except BaseException as e:  # noqa: BLE001 — shipped to caller
                    # Raising handlers count too — they are exactly the
                    # ones an operator reads event_stats to find.
                    self._record_stat(req["m"], time.perf_counter() - t0)
                    _send_msg(
                        conn,
                        {"ok": False, "e": e, "tb": traceback.format_exc()},
                    )
        except (ConnectionLost, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def stop(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # Drop established connections too: a stopped server must release
        # the port fully (head restart binds the same address) and stop
        # serving — peers reconnect to whoever binds it next.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class RpcClient:
    """Thread-safe client; one pooled connection per calling thread (so
    concurrent calls don't interleave frames, and per-thread call order is
    preserved end-to-end).

    ``reconnect_window`` > 0 makes calls retry on connection loss for that
    many seconds before failing — used for head clients so a head restart
    (GCS fault tolerance) is invisible to agents/workers/drivers. Only
    safe for idempotent calls (all head mutations are: tables are keyed by
    caller-generated ids and writes are last-write-wins)."""

    def __init__(self, address: str, timeout: float = 60.0,
                 reconnect_window: float = 0.0,
                 token: bytes | None = None):
        self.address = address
        self._timeout = timeout
        self._reconnect_window = reconnect_window
        self._token = get_cluster_token() if token is None else token
        self._local = threading.local()
        self._closed = False

    def _conn(self) -> socket.socket:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            host, port = self.address.rsplit(":", 1)
            conn = socket.create_connection((host, int(port)), timeout=self._timeout)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                self._handshake_client(conn)
            except BaseException:
                conn.close()
                raise
            self._local.conn = conn
        return conn

    def _handshake_client(self, conn: socket.socket) -> None:
        hello = _recv_exact(conn, 38)
        if hello[:5] != b"RTPA1":
            raise ConnectionLost(
                f"{self.address}: not a ray_tpu RPC server")
        required, challenge = hello[5:6], hello[6:]
        if required == b"\x00":
            if self._token:
                # A token-configured client must never talk to an
                # unauthenticated server: a spoofed listener on a dead
                # peer's port would otherwise downgrade us into feeding
                # its frames to pickle.
                raise AuthError(
                    f"{self.address} does not require the cluster token "
                    f"this client is configured with (spoofed server?)"
                )
            return
        if not self._token:
            raise AuthError(
                f"{self.address} requires a cluster token "
                f"(set RAY_TPU_CLUSTER_TOKEN)"
            )
        client_nonce = os.urandom(32)
        conn.sendall(
            hmac.new(self._token, challenge, hashlib.sha256).digest()
            + client_nonce)
        reply = _recv_exact(conn, 33)  # verdict || server proof
        if reply[:1] != b"\x01":
            raise AuthError(f"{self.address} rejected the cluster token")
        expect = hmac.new(
            self._token, challenge + client_nonce, hashlib.sha256).digest()
        if not hmac.compare_digest(reply[1:], expect):
            raise AuthError(
                f"{self.address} failed to prove the cluster token "
                f"(spoofed server?)"
            )

    def call(self, method: str, *args, timeout: float | None = None, **kwargs):
        deadline = (
            time.monotonic() + self._reconnect_window
            if self._reconnect_window > 0 else None
        )
        while True:
            try:
                return self._call_once(method, args, kwargs, timeout)
            except ConnectionLost:
                if (deadline is None or self._closed
                        or time.monotonic() >= deadline):
                    raise
                time.sleep(0.3)

    def _call_once(self, method: str, args, kwargs, timeout: float | None):
        if self._closed:
            raise ConnectionLost(f"client to {self.address} is closed")
        try:
            # Connect inside the ConnectionLost mapping: a refused
            # reconnect (server restarting) must feed the retry window,
            # not escape it as a bare OSError.
            conn = self._conn()
        except OSError as e:
            raise ConnectionLost(
                f"connect to {self.address}: {e}") from e
        if timeout is not None:
            conn.settimeout(timeout)
        sent = False
        try:
            _send_msg(conn, {"m": method, "a": args, "k": kwargs})
            sent = True
            resp = _recv_msg(conn)
        except (OSError, EOFError, ConnectionLost) as e:
            self._drop_conn()
            err = ConnectionLost(f"rpc {method} to {self.address}: {e}")
            # Callers with non-idempotent requests need to know whether
            # the peer might have EXECUTED this call. A connect/send
            # failure cannot have (a partial length-prefixed frame never
            # decodes); only a lost reply after a complete send is
            # ambiguous.
            err.maybe_executed = sent
            raise err from e
        finally:
            if timeout is not None:
                try:
                    conn.settimeout(self._timeout)
                except OSError:
                    pass
        if resp["ok"]:
            return resp["v"]
        raise resp["e"]

    def _drop_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def close(self):
        self._closed = True
        self._drop_conn()

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *a, **k: self.call(name, *a, **k)
