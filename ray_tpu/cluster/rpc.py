"""Threaded RPC: schema'd msgpack frames over TCP, with streaming.

Plays the role of the reference's gRPC scaffolding (``src/ray/rpc/``):
request/response with per-connection FIFO ordering (the property the direct
actor transport relies on for in-order actor calls,
``direct_actor_task_submitter.h``) plus server-streaming calls (the
reference's gRPC server-streaming, e.g. object-chunk/log streams).
Handlers run on a thread per connection; blocking handlers (long-poll
style) are therefore fine.

Wire format (round 5, replaces pickle-on-the-wire): 4-byte big-endian
length || msgpack frame (``wire.WireCodec``). Requests are
``{"m": method, "a": args, "k": kwargs[, "st": true][, "tp": traceparent]}``
(``tp`` is a W3C traceparent carried only when the calling thread has an
active trace — the server parents an ``rpc:<method>`` span under it, so
one trace id follows a request across every RPC hop); responses
``{"ok": true, "v": value}`` / ``{"ok": false, "e": exc, "tb": str}``;
streaming responses are ``{"ok": true, "stream": true}`` followed by one
``{"s": item}`` frame per yielded item and ``{"end": true}``. Hot-path
messages (task-spec batches, schedule requests, heartbeats, location
waits, object chunks) are pure primitive structures and encode natively;
user payloads stay opaque cloudpickle bytes; arbitrary rich objects need
the authenticated pickle extension (see ``wire.py`` for the threat
model).

Authentication: when a cluster token is configured (``RAY_TPU_CLUSTER_TOKEN``
/ ``config.cluster_token`` — auto-generated per cluster since round 5),
every server sends a random challenge on accept and requires
``HMAC-SHA256(token, challenge)`` back before serving — and only
authenticated connections may carry the pickle extension. The hello
frame is sent either way so token/no-token peers fail fast instead of
deadlocking.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import random
import socket
import struct
import threading
import time
import traceback
from typing import Any, Callable, Iterator

from contextlib import nullcontext

from ray_tpu.cluster.wire import WireCodec, WireError
from ray_tpu.util import tracing as _tracing

_LEN = struct.Struct(">I")

# Sanity cap on a single frame (defense against a hostile/corrupt length
# prefix committing us to unbounded allocation). Object-plane chunks are
# 4 MiB; function blobs and inlined objects stay well under this.
MAX_FRAME_BYTES = 1 << 30


def get_cluster_token() -> bytes:
    from ray_tpu.core.config import config

    return config.cluster_token.encode()


def _outbound_traceparent() -> str | None:
    """The W3C traceparent an outbound request should carry: set only
    when this thread is inside an active span (suppressed control-plane
    cadence traffic, and everything while tracing is off, rides bare —
    the envelope cost is zero unless a request is actually traced)."""
    if not _tracing.is_enabled() or _tracing.is_suppressed():
        return None
    return _tracing.format_traceparent(_tracing.current_context())


def ensure_cluster_token() -> str:
    """Make authenticated-by-default clusters: called at cluster
    formation, generates a random per-cluster token when none is
    configured, and exports it so worker/agent subprocesses inherit it.

    An operator can still run auth-off by EXPLICITLY setting
    ``RAY_TPU_CLUSTER_TOKEN=""`` (present-but-empty) — the insecure
    posture must be chosen, never defaulted into (the reference's
    historical default, see ShadowRay, is the cautionary tale)."""
    from ray_tpu.core.config import config

    raw = os.environ.get("RAY_TPU_CLUSTER_TOKEN")
    if raw is not None:
        config.override("cluster_token", raw)
        return raw
    if config.cluster_token:
        # Configured via config.override: still export, or spawned
        # worker subprocesses would read an empty token and fail auth.
        os.environ["RAY_TPU_CLUSTER_TOKEN"] = config.cluster_token
        return config.cluster_token
    tok = os.urandom(16).hex()
    config.override("cluster_token", tok)
    os.environ["RAY_TPU_CLUSTER_TOKEN"] = tok
    return tok


class AuthError(Exception):
    """The peer failed (or refused) the cluster-token handshake."""


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


# -- network chaos (deterministic fault injection on the RPC plane) -----
#
# A process-global policy table consulted by every RpcClient call. Rules
# match on (source endpoint tag, destination address, method prefix) and
# inject one of four faults. Every injected fault surfaces as
# ConnectionLost — exactly what a real network failure produces — never
# as silent corruption:
#
#   delay      sleep a (seeded) uniform draw from [lo, hi] before sending
#   drop       the call never reaches the peer (partition semantics);
#              retry-windowed callers keep retrying until the window ends
#              or the rule is removed (heal), so a partition shorter than
#              the reconnect window is invisible to the application
#   sever      the request is FULLY sent, then the connection is severed
#              before the reply — the peer executes, the caller sees
#              ConnectionLost with maybe_executed=True (the at-most-once
#              ambiguity path every non-idempotent caller must handle)
#   duplicate  the call is made twice (second reply discarded): exercises
#              task-id dup-suppression on the receiver
#
# Sources are identified by an endpoint tag (`RpcClient.chaos_src`) set
# by whoever owns the client — the head tags its per-node clients with
# the head address, agents tag theirs with the agent address, drivers
# with their owner-directory address — so `Cluster.partition(groups)`
# can arm SYMMETRIC drop rules between address sets and heartbeats,
# gossip, fan-outs, and object traffic all genuinely observe the
# partition. Untagged clients only match rules with src=None.


class ChaosRule:
    __slots__ = ("rule_id", "src", "dst", "method", "action", "arg",
                 "prob", "label", "times")

    def __init__(self, action: str, *, src=None, dst=None, method=None,
                 arg=None, prob: float = 1.0, label: str = "",
                 times: int | None = None, rule_id: int = 0):
        if action not in ("delay", "drop", "sever", "duplicate"):
            raise ValueError(f"unknown chaos action {action!r}")
        self.rule_id = rule_id
        # A bare string is one address, not an iterable of characters —
        # frozenset("host:port") would silently never match anything.
        if isinstance(src, str):
            src = (src,)
        if isinstance(dst, str):
            dst = (dst,)
        self.src = frozenset(src) if src else None
        self.dst = frozenset(dst) if dst else None
        self.method = method  # exact method name or prefix ending in '*'
        self.action = action
        self.arg = arg  # delay: (lo, hi) seconds
        self.prob = prob
        self.label = label
        # Firing budget: the rule expires after this many injections
        # (None = unlimited). times=1 gives one-shot faults — e.g. sever
        # exactly one push, then let the retry through.
        self.times = times

    def matches(self, src, dst: str, method: str) -> bool:
        if self.src is not None and src not in self.src:
            return False
        if self.dst is not None and dst not in self.dst:
            return False
        if self.method:
            if self.method.endswith("*"):
                if not method.startswith(self.method[:-1]):
                    return False
            elif method != self.method:
                return False
        return True

    def describe(self) -> dict:
        return {
            "rule_id": self.rule_id,
            "src": sorted(self.src) if self.src else None,
            "dst": sorted(self.dst) if self.dst else None,
            "method": self.method,
            "action": self.action,
            "arg": list(self.arg) if isinstance(self.arg, tuple)
            else self.arg,
            "prob": self.prob,
            "label": self.label,
            "times": self.times,
        }


class ChannelChaos:
    """Process-global chaos policy for the RPC plane. Zero-cost when
    empty: callers gate on the plain ``active`` flag before touching the
    lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: list[ChaosRule] = []
        self._next_id = 1
        self._rng = None
        self._rng_seed = None
        self.active = False  # lock-free fast-path gate

    def _ensure_rng(self):
        # Rebuilt whenever the effective chaos seed changes: a draw made
        # before RAY_TPU_CHAOS_SEED was set must not pin an unseeded RNG
        # for the process lifetime (same-seed replay would diverge).
        from ray_tpu.util.failpoints import effective_seed, seeded_rng

        seed = effective_seed()
        if self._rng is None or seed != self._rng_seed:
            self._rng = seeded_rng("channel-chaos")
            self._rng_seed = seed
        return self._rng

    def add_rule(self, action: str, *, src=None, dst=None, method=None,
                 arg=None, prob: float = 1.0, label: str = "",
                 times: int | None = None) -> int:
        with self._lock:
            rule = ChaosRule(action, src=src, dst=dst, method=method,
                             arg=arg, prob=prob, label=label,
                             times=times, rule_id=self._next_id)
            self._next_id += 1
            self._rules.append(rule)
            self.active = True
            return rule.rule_id

    def add_rule_dict(self, rec: dict) -> int:
        """Wire-shaped rule (the control-plane fanout ships dicts).
        IDEMPOTENT: an identical rule already armed is not added again —
        on an in-process cluster the head's fanout reaches the same
        process-global table once per agent, and a ``times``-budgeted
        one-shot must not silently become an N-shot."""
        arg = rec.get("arg")
        if isinstance(arg, (list, tuple)):
            arg = tuple(arg)
        key = (rec["action"],
               frozenset(rec.get("src") or ()) or None,
               frozenset(rec.get("dst") or ()) or None,
               rec.get("method"), arg, rec.get("prob", 1.0),
               rec.get("label", ""), rec.get("times"))
        with self._lock:
            for r in self._rules:
                if (r.action, r.src, r.dst, r.method, r.arg, r.prob,
                        r.label, r.times) == key:
                    return r.rule_id
        return self.add_rule(
            rec["action"], src=rec.get("src"), dst=rec.get("dst"),
            method=rec.get("method"), arg=arg,
            prob=rec.get("prob", 1.0), label=rec.get("label", ""),
            times=rec.get("times"))

    def add_rule_dicts(self, rules: list, label: str = "") -> int:
        """Arm a batch of wire-shaped rules, folding ``label`` into any
        rule that lacks one — the one arming loop every control-plane
        surface (head, agent, worker) shares. Returns the count armed
        (idempotent re-arms included)."""
        n = 0
        for rec in rules:
            if label and not rec.get("label"):
                rec = dict(rec, label=label)
            self.add_rule_dict(rec)
            n += 1
        return n

    def remove(self, rule_id: int) -> bool:
        with self._lock:
            before = len(self._rules)
            self._rules = [r for r in self._rules if r.rule_id != rule_id]
            self.active = bool(self._rules)
            return len(self._rules) != before

    def clear(self, label: str | None = None) -> int:
        with self._lock:
            before = len(self._rules)
            if label is None:
                self._rules = []
            else:
                self._rules = [r for r in self._rules if r.label != label]
            self.active = bool(self._rules)
            return before - len(self._rules)

    def match(self, src, dst: str, method: str, actions=None):
        """First matching rule that passes its probability draw; rules
        with a ``times`` budget expire once it is spent. ``actions``
        restricts which rule actions are considered at all — callers
        that cannot apply an action (streams can't sever/duplicate)
        must not consume its firing budget."""
        with self._lock:
            for rule in self._rules:
                if actions is not None and rule.action not in actions:
                    continue
                if rule.matches(src, dst, method):
                    if rule.prob < 1.0 and \
                            self._ensure_rng().random() >= rule.prob:
                        continue
                    if rule.times is not None:
                        rule.times -= 1
                        if rule.times <= 0:
                            self._rules.remove(rule)
                            self.active = bool(self._rules)
                    return rule
            return None

    def delay_draw(self, arg) -> float:
        lo, hi = (arg if isinstance(arg, tuple) and len(arg) == 2
                  else (arg or 0.05, arg or 0.05))
        lo, hi = float(lo), float(hi)
        if hi <= lo:
            return lo
        with self._lock:
            return self._ensure_rng().uniform(lo, hi)

    def describe(self) -> list[dict]:
        with self._lock:
            return [r.describe() for r in self._rules]


channel_chaos = ChannelChaos()

# The chaos CONTROL plane rides above the chaos it arms: arming, healing
# and listing RPCs are exempt from injection. Otherwise a partition rule
# would drop its own fan-out to far-side agents (leaving their workers
# unarmed and the "partition" one-directional) and heal could never
# reach a partitioned peer to clear it.
CHAOS_CONTROL_METHODS = frozenset((
    "set_channel_chaos", "clear_channel_chaos", "list_channel_chaos",
    "set_failpoints", "list_failpoints",
))


class _ChaosSevered(Exception):
    """Internal: the chaos policy severed this connection after a
    complete send (mapped to ConnectionLost with maybe_executed=True)."""


def _send_msg(sock: socket.socket, obj: Any, codec: WireCodec) -> None:
    blob = codec.packb(obj)
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionLost("peer closed connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket, codec: WireCodec) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME_BYTES:
        # The unread body makes the stream unframeable from here on:
        # drop the connection rather than try to resync.
        raise ConnectionLost(f"frame length {length} exceeds cap")
    return codec.unpackb(_recv_exact(sock, length))


class RpcServer:
    """Serves ``rpc_<method>`` methods of a handler object."""

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0,
                 token: bytes | None = None, rpc_histogram=None):
        self._handler = handler
        self._token = get_cluster_token() if token is None else token
        # Optional per-method latency histogram (a metrics.Histogram with
        # a "method" tag key): the head passes ray_tpu_head_rpc_seconds
        # so handler latency lands on the federated scrape; agents skip
        # it (their per-method stats stay in handler_stats only).
        self._rpc_histogram = rpc_histogram
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(512)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stopped = threading.Event()
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        # Handler instrumentation (reference: the asio instrumented event
        # loop's per-handler stats, src/ray/common/asio event_stats.h):
        # per-method call count / cumulative / max seconds, cheap enough
        # to stay always-on.
        self._stats: dict[str, list] = {}  # method -> [count, total_s, max_s]
        self._stats_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _record_stat(self, method: str, dt: float) -> None:
        with self._stats_lock:
            ent = self._stats.get(method)
            if ent is None:
                self._stats[method] = [1, dt, dt]
            else:
                ent[0] += 1
                ent[1] += dt
                if dt > ent[2]:
                    ent[2] = dt
        if self._rpc_histogram is not None:
            try:
                self._rpc_histogram.observe(dt, tags={"method": method})
            except Exception:
                pass  # instrumentation must never fail a handler

    def handler_stats(self) -> dict:
        """{method: {count, total_s, max_s, mean_ms}} snapshot."""
        with self._stats_lock:
            return {
                m: {
                    "count": c, "total_s": round(t, 6),
                    "max_s": round(mx, 6),
                    "mean_ms": round(1000.0 * t / c, 3) if c else 0.0,
                }
                for m, (c, t, mx) in self._stats.items()
            }

    def _accept_loop(self):
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._stopped.is_set():
                    # Raced stop(): it already swept the set — this conn
                    # must not outlive the server (head-restart correctness).
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _handshake_server(self, conn: socket.socket) -> bool:
        """Raw-byte MUTUAL hello/challenge exchange — runs BEFORE any
        pickle frame, so unauthenticated bytes never reach the
        deserializer. The server also proves token knowledge over the
        client's nonce, so a spoofed server (e.g. an attacker binding a
        dead head's port) cannot downgrade reconnecting peers."""
        challenge = os.urandom(32)
        required = b"\x01" if self._token else b"\x00"
        try:
            conn.sendall(b"RTPA1" + required + challenge)
            if not self._token:
                return True
            frame = _recv_exact(conn, 64)  # digest || client nonce
            digest, client_nonce = frame[:32], frame[32:]
            expect = hmac.new(
                self._token, challenge, hashlib.sha256).digest()
            ok = hmac.compare_digest(digest, expect)
            # The proof is bound to BOTH nonces and only sent to a client
            # that proved token knowledge first. Either property alone
            # stops the relay attack (a MITM forwarding our nonce to a
            # live server with a garbage digest to harvest a proof);
            # belt-and-braces we do both.
            if ok:
                proof = hmac.new(
                    self._token, challenge + client_nonce,
                    hashlib.sha256).digest()
            else:
                proof = bytes(32)
            conn.sendall((b"\x01" if ok else b"\x00") + proof)
            return ok
        except (ConnectionLost, OSError):
            return False

    def _serve_conn(self, conn: socket.socket):
        codec = WireCodec(allow_pickle=bool(self._token))
        try:
            if not self._handshake_server(conn):
                return
            while True:
                try:
                    req = _recv_msg(conn, codec)
                except WireError as e:
                    # The frame was length-delimited and fully consumed,
                    # so framing is intact: answer the error and keep
                    # serving (a fuzzer/buggy peer can't kill the conn
                    # for its co-tenants; there are none — but FIFO
                    # requires one response per request regardless).
                    _send_msg(conn, {"ok": False, "e": e, "tb": ""}, codec)
                    continue
                if not isinstance(req, dict) or "m" not in req \
                        or not isinstance(req.get("m"), str):
                    _send_msg(conn, {
                        "ok": False,
                        "e": WireError("malformed request envelope"),
                        "tb": "",
                    }, codec)
                    continue
                t0 = time.perf_counter()
                try:
                    fn = getattr(self._handler, "rpc_" + req["m"])
                    # Trace propagation: a request carrying a W3C
                    # traceparent parents an rpc:<method> span on this
                    # side of the hop (only when this process traces —
                    # the sampling decision belongs to the server, and
                    # spans opened by the handler nest under it via the
                    # thread-local current span).
                    parent = _tracing.parse_traceparent(req.get("tp")) \
                        if req.get("tp") and _tracing.is_enabled() \
                        else None
                    span_cm = _tracing.span(
                        "rpc:" + req["m"], parent=parent, cat="rpc") \
                        if parent is not None else nullcontext()
                    with span_cm:
                        value = fn(*req.get("a", ()), **req.get("k", {}))
                        if req.get("st"):
                            self._stream_response(conn, codec, value)
                            self._record_stat(
                                req["m"], time.perf_counter() - t0)
                            continue
                        if hasattr(value, "__next__"):
                            # Streaming handler invoked without st:
                            # drain so the reply is still one frame.
                            value = list(value)
                    self._record_stat(req["m"], time.perf_counter() - t0)
                    try:
                        _send_msg(conn, {"ok": True, "v": value}, codec)
                    except WireError as e:
                        # Encoding the reply failed locally (strict
                        # profile, rich object): nothing was written, so
                        # convert to an error response in its place.
                        _send_msg(
                            conn, {"ok": False, "e": e, "tb": ""}, codec)
                except ConnectionLost:
                    raise
                # A raising handler is NORMAL control flow here (typed
                # sheds, infeasible bundles — the error ships to the
                # caller and event_stats records it); ticking the
                # loop-restart series for each would read as a crash
                # cycle under an ordinary shed storm.
                except BaseException as e:  # noqa: BLE001 — shipped to caller  # analyze: ignore[DL002]
                    self._record_stat(req["m"], time.perf_counter() - t0)
                    _send_msg(
                        conn,
                        {"ok": False, "e": e, "tb": traceback.format_exc()},
                        codec,
                    )
        except (ConnectionLost, WireError, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    def _stream_response(self, conn: socket.socket, codec: WireCodec,
                         value: Any) -> None:
        """Server-streaming reply: one frame per yielded item. The
        header goes out before the first item is pulled, so the client
        can start consuming while the handler produces."""
        _send_msg(conn, {"ok": True, "stream": True}, codec)
        try:
            for item in iter(value):
                _send_msg(conn, {"s": item}, codec)
        except ConnectionLost:
            raise
        except BaseException as e:  # noqa: BLE001 — shipped to caller
            _send_msg(
                conn, {"ok": False, "e": e, "tb": traceback.format_exc()},
                codec)
            return
        _send_msg(conn, {"end": True}, codec)

    def stop(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        # Drop established connections too: a stopped server must release
        # the port fully (head restart binds the same address) and stop
        # serving — peers reconnect to whoever binds it next.
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


class RpcClient:
    """Thread-safe client; one pooled connection per calling thread (so
    concurrent calls don't interleave frames, and per-thread call order is
    preserved end-to-end).

    ``reconnect_window`` > 0 makes calls retry on connection loss for that
    many seconds before failing — used for head clients so a head restart
    (GCS fault tolerance) is invisible to agents/workers/drivers. Only
    safe for idempotent calls (all head mutations are: tables are keyed by
    caller-generated ids and writes are last-write-wins)."""

    # Reconnect backoff: jittered exponential, 50ms -> 1s cap (+/-25%).
    # A flat retry interval synchronizes every reconnecting peer into
    # thundering-herd rounds against a restarting head.
    RECONNECT_BASE_S = 0.05
    RECONNECT_CAP_S = 1.0

    def __init__(self, address: str, timeout: float = 60.0,
                 reconnect_window: float = 0.0,
                 token: bytes | None = None):
        self.address = address
        self._timeout = timeout
        self._reconnect_window = reconnect_window
        self._token = get_cluster_token() if token is None else token
        self._local = threading.local()
        self._closed = False
        # Chaos source tag: the owning endpoint's address (set by whoever
        # created this client), matched against ChannelChaos rule src
        # sets. None = untagged (matches only src-wildcard rules).
        self.chaos_src: str | None = None

    def _codec(self) -> WireCodec:
        codec = getattr(self._local, "codec", None)
        if codec is None:
            codec = self._local.codec = WireCodec(
                allow_pickle=bool(self._token))
        return codec

    def _new_socket(self) -> socket.socket:
        host, port = self.address.rsplit(":", 1)
        conn = socket.create_connection(
            (host, int(port)), timeout=self._timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            self._handshake_client(conn)
        except BaseException:
            conn.close()
            raise
        return conn

    def _conn(self) -> socket.socket:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._local.conn = self._new_socket()
        return conn

    def _handshake_client(self, conn: socket.socket) -> None:
        hello = _recv_exact(conn, 38)
        if hello[:5] != b"RTPA1":
            raise ConnectionLost(
                f"{self.address}: not a ray_tpu RPC server")
        required, challenge = hello[5:6], hello[6:]
        if required == b"\x00":
            if self._token:
                # A token-configured client must never talk to an
                # unauthenticated server: a spoofed listener on a dead
                # peer's port would otherwise downgrade us into feeding
                # its frames to pickle.
                raise AuthError(
                    f"{self.address} does not require the cluster token "
                    f"this client is configured with (spoofed server?)"
                )
            return
        if not self._token:
            raise AuthError(
                f"{self.address} requires a cluster token "
                f"(set RAY_TPU_CLUSTER_TOKEN)"
            )
        client_nonce = os.urandom(32)
        conn.sendall(
            hmac.new(self._token, challenge, hashlib.sha256).digest()
            + client_nonce)
        reply = _recv_exact(conn, 33)  # verdict || server proof
        if reply[:1] != b"\x01":
            raise AuthError(f"{self.address} rejected the cluster token")
        expect = hmac.new(
            self._token, challenge + client_nonce, hashlib.sha256).digest()
        if not hmac.compare_digest(reply[1:], expect):
            raise AuthError(
                f"{self.address} failed to prove the cluster token "
                f"(spoofed server?)"
            )

    def call(self, method: str, *args, timeout: float | None = None, **kwargs):
        deadline = (
            time.monotonic() + self._reconnect_window
            if self._reconnect_window > 0 else None
        )
        attempt = 0
        while True:
            sever = duplicate = False
            if channel_chaos.active and method not in CHAOS_CONTROL_METHODS:
                rule = channel_chaos.match(
                    self.chaos_src, self.address, method)
                if rule is not None:
                    if rule.action == "delay":
                        time.sleep(channel_chaos.delay_draw(rule.arg))
                    elif rule.action == "drop":
                        # The request never reaches the peer. Surfaces
                        # as ConnectionLost below so retry-windowed
                        # callers keep probing (and succeed on heal).
                        err = ConnectionLost(
                            f"rpc {method} to {self.address}: "
                            f"chaos drop (partitioned)")
                        err.maybe_executed = False
                        if (deadline is None or self._closed
                                or time.monotonic() >= deadline):
                            raise err
                        attempt += 1
                        self._reconnect_sleep(attempt)
                        continue
                    elif rule.action == "sever":
                        sever = True
                    elif rule.action == "duplicate":
                        duplicate = True
            try:
                result = self._call_once(
                    method, args, kwargs, timeout, chaos_sever=sever)
                if duplicate:
                    # Duplicate delivery: the same request again, reply
                    # discarded — the receiver's dup-suppression is the
                    # thing under test. Failures of the duplicate never
                    # surface.
                    try:
                        self._call_once(method, args, kwargs, timeout)
                    except (ConnectionLost, RpcError, OSError):
                        pass
                return result
            except ConnectionLost:
                # Retrying ambiguous losses (maybe_executed) here is safe
                # by this class's contract: reconnect_window is only set
                # on clients whose calls are idempotent (head tables are
                # keyed by caller-generated ids, last-write-wins).
                if (deadline is None or self._closed
                        or time.monotonic() >= deadline):
                    raise
                attempt += 1
                self._reconnect_sleep(attempt)

    def _reconnect_sleep(self, attempt: int) -> None:
        """Jittered exponential backoff between reconnect attempts, and
        one counter tick so reconnect storms are visible on the
        federated scrape."""
        try:
            from ray_tpu.util import metrics as _metrics

            _metrics.RPC_RECONNECTS_TOTAL.inc(
                tags={"peer": self.address})
        except Exception:
            pass
        delay = min(self.RECONNECT_CAP_S,
                    self.RECONNECT_BASE_S * (2 ** (attempt - 1)))
        time.sleep(delay * random.uniform(0.75, 1.25))

    def _call_once(self, method: str, args, kwargs, timeout: float | None,
                   chaos_sever: bool = False):
        if self._closed:
            raise ConnectionLost(f"client to {self.address} is closed")
        try:
            # Connect inside the ConnectionLost mapping: a refused
            # reconnect (server restarting) must feed the retry window,
            # not escape it as a bare OSError. LOOPBACK connect TIMEOUTS
            # get bounded retries: on localhost a timeout means the
            # server's accept loop is CPU-starved (fork storms on a
            # shared-core box), not that the peer is gone, and no request
            # was sent so retrying is safe. Remote-host timeouts fail
            # fast like refusals — a crashed/partitioned HOST times out
            # rather than refusing, and tripling failover latency for
            # every dead peer (gossip, spillback, owner polls) would
            # multiply across their single-threaded consumers.
            retry_connect = self.address.startswith(
                ("127.", "localhost:"))
            conn = None
            for attempt in range(3 if retry_connect else 1):
                try:
                    conn = self._conn()
                    break
                except (socket.timeout, TimeoutError):
                    if not retry_connect or attempt == 2:
                        raise
                    time.sleep(0.5 * (attempt + 1))
        except OSError as e:
            raise ConnectionLost(
                f"connect to {self.address}: {e}") from e
        codec = self._codec()
        if timeout is not None:
            conn.settimeout(timeout)
        sent = False
        try:
            # args as a list: skips one EXT_TUPLE nesting per message on
            # the hottest path (the server *-unpacks either shape).
            req = {"m": method, "a": list(args), "k": kwargs}
            tp = _outbound_traceparent()
            if tp:
                req["tp"] = tp
            _send_msg(conn, req, codec)
            sent = True
            if chaos_sever:
                # Network chaos: the request is fully on the wire (the
                # peer WILL execute it) but the reply path dies — the
                # strongest form of the maybe_executed ambiguity.
                raise _ChaosSevered(
                    f"chaos sever after send of {method}")
            resp = _recv_msg(conn, codec)
            # (No "stream" handling here: without the "st" flag the
            # server drains generator handlers itself and replies with
            # one list-valued frame.)
        except (OSError, EOFError, ConnectionLost, _ChaosSevered) as e:
            self._drop_conn()
            err = ConnectionLost(f"rpc {method} to {self.address}: {e}")
            # Callers with non-idempotent requests need to know whether
            # the peer might have EXECUTED this call. A connect/send
            # failure cannot have (a partial length-prefixed frame never
            # decodes); only a lost reply after a complete send is
            # ambiguous.
            err.maybe_executed = sent
            raise err from e
        finally:
            if timeout is not None:
                try:
                    conn.settimeout(self._timeout)
                except OSError:
                    pass
        if resp["ok"]:
            return resp["v"]
        raise resp["e"]

    def call_stream(self, method: str, *args,
                    timeout: float | None = None, **kwargs) -> Iterator:
        """Server-streaming call: yields items as the handler produces
        them (the reference's gRPC server-streaming analog). Runs on a
        DEDICATED connection so a long-lived stream (log following,
        object chunks) never blocks this thread's request channel; the
        socket closes when the generator is exhausted or closed."""
        if self._closed:
            raise ConnectionLost(f"client to {self.address} is closed")
        if channel_chaos.active and method not in CHAOS_CONTROL_METHODS:
            rule = channel_chaos.match(
                self.chaos_src, self.address, method,
                actions=("drop", "delay"))
            if rule is not None:
                # Streams keep chaos simple: drop raises (a partitioned
                # peer's stream can't start), delay defers the start;
                # sever/duplicate don't apply to streaming calls.
                if rule.action == "drop":
                    raise ConnectionLost(
                        f"stream {method} to {self.address}: "
                        f"chaos drop (partitioned)")
                if rule.action == "delay":
                    time.sleep(channel_chaos.delay_draw(rule.arg))
        codec = WireCodec(allow_pickle=bool(self._token))
        try:
            conn = self._new_socket()
        except OSError as e:
            raise ConnectionLost(f"connect to {self.address}: {e}") from e
        if timeout is not None:
            conn.settimeout(timeout)

        stream_req = {"m": method, "a": list(args), "k": kwargs,
                      "st": True}
        # Capture the traceparent HERE, not inside gen(): the stream is
        # consumed lazily, possibly on another thread with no trace
        # context.
        tp = _outbound_traceparent()
        if tp:
            stream_req["tp"] = tp

        def gen():
            try:
                _send_msg(conn, stream_req, codec)
                first = _recv_msg(conn, codec)
                if not first.get("stream"):
                    if first.get("ok"):
                        # Non-streaming handler: behave as a 1-item
                        # (or len(list)-item) stream.
                        value = first["v"]
                        if isinstance(value, list):
                            yield from value
                        else:
                            yield value
                        return
                    raise first["e"]
                while True:
                    frame = _recv_msg(conn, codec)
                    if "s" in frame:
                        yield frame["s"]
                    elif frame.get("end"):
                        return
                    else:
                        raise frame["e"]
            except (OSError, EOFError) as e:
                raise ConnectionLost(
                    f"stream {method} to {self.address}: {e}") from e
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

        return gen()

    def _drop_conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def close(self):
        self._closed = True
        self._drop_conn()

    def __getattr__(self, name: str) -> Callable:
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda *a, **k: self.call(name, *a, **k)
