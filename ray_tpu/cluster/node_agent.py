"""Node agent: per-node daemon (raylet equivalent).

Mirrors ``src/ray/raylet/node_manager.h``: owns the node's resources and
worker processes. Implements:

  * worker pool — forked Python worker processes, cached when idle
    (``worker_pool.h:80``); a dead worker's in-flight task is failed by
    storing an error object (the owner then retries);
  * local task dispatch — FIFO queue + blocking resource acquisition, the
    LocalTaskManager analog;
  * placement-group bundle 2PC participant — prepare/commit/return
    (``node_manager.proto:375`` PrepareBundleResources/CommitBundleResources);
  * local object store — creates this node's C++ shm segment and serves
    object bytes to peer nodes (``ObjectManager::Push`` analog, pull-based);
  * heartbeats to the head with the live resource view
    (``gcs_heartbeat_manager.h``).
"""

from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
import time
from typing import Optional

from ray_tpu._native.shm_store import ShmStore
from ray_tpu.cluster.rpc import (
    ConnectionLost,
    RpcClient,
    RpcServer,
    channel_chaos,
)
from ray_tpu.core import ids
from ray_tpu.util import failpoints
from ray_tpu.core.object_ref import ObjectLostError
from ray_tpu.core.config import config
from ray_tpu.core.resources import ResourcePool

DEFAULT_STORE_CAPACITY = config.object_store_capacity_bytes


class _Worker:
    def __init__(self, worker_id, proc, address=None, env_key=""):
        self.worker_id = worker_id
        self.proc = proc
        self.address = address
        self.started_at = time.time()
        self.client: Optional[RpcClient] = None
        self.client_id: Optional[str] = None  # ref-table holder id
        self.ready = threading.Event()
        self.current_task = None  # (task_spec, release_fn) while executing
        self.is_actor = False
        self.actor_id = None
        # Runtime-env hash this process was spawned under; the pool never
        # leases a worker across env keys ("" = plain environment).
        self.env_key = env_key


class NodeAgent:
    def __init__(
        self,
        head_address: str,
        *,
        num_cpus: float | None = None,
        resources: dict | None = None,
        store_capacity: int = DEFAULT_STORE_CAPACITY,
        host: str = "127.0.0.1",
        session: str | None = None,
        memory_usage_threshold: float | None = None,
        memory_limit_bytes: int | None = None,
        labels: dict | None = None,
    ):
        self.node_id = ids.new_node_id()
        # Provisioning metadata (node_type, spot, ...) carried to the
        # head at registration; the autoscaler and status surfaces read
        # it from the node table. A spot node's preemption still arrives
        # through the preemption watcher / SIGTERM — labels only say
        # WHICH nodes can vanish that way.
        self.labels = dict(labels or {})
        self.head_address = head_address
        # Reconnect window so a restarting head (GCS FT) doesn't fail
        # in-flight add_location/register calls from this agent.
        self.head = RpcClient(
            head_address, reconnect_window=config.head_reconnect_window_s)
        node_res = {"CPU": float(num_cpus if num_cpus is not None else os.cpu_count() or 8)}
        node_res.update(resources or {})
        self.pool = ResourcePool(node_res)
        self.total_resources = dict(node_res)
        session = session or f"s{os.getpid()}"
        self.store_path = f"/dev/shm/ray_tpu_{session}_{self.node_id[-8:]}"
        self.store = ShmStore(self.store_path, store_capacity, create=True)
        # Spill target (external_storage.py:72 analog): cold primary
        # copies move here under memory pressure; restored on demand.
        # Default: a per-session local dir (dies with the node). With
        # config.spill_uri set, spills go to the shared remote backend
        # and the head records them so a DEAD node's spilled objects
        # restore from the URI instead of recomputing (spill_storage.py).
        from ray_tpu.cluster import spill_storage

        self.spill_dir = f"/tmp/ray_tpu_spill_{session}_{self.node_id[-8:]}"
        spill_uri = config.spill_uri
        if spill_uri:
            # A typo'd URI must fail agent boot, not the first
            # memory-pressure spill.
            self.spill_backend = spill_storage.backend_for(spill_uri)
        else:
            self.spill_backend = spill_storage.local_backend(self.spill_dir)
        self._spill_lock = threading.Lock()
        # Foreign-URI restore backends (rpc_restore_from_uri for objects
        # another node spilled under a different/older spill_uri),
        # bounded small — a cluster normally has ONE spill target.
        self._restore_backends: dict[str, object] = {}
        self._deferred_deletes: set[str] = set()

        self._lock = threading.RLock()
        self._workers: dict[str, _Worker] = {}
        # Idle pools keyed by runtime-env hash (worker_pool.cc keys its
        # pools by runtime-env hash the same way; "" = no runtime env).
        self._idle: dict[str, list[_Worker]] = {}
        self._max_workers = max(
            config.worker_min_pool,
            int(node_res.get("CPU", 4)) * config.workers_per_cpu,
        )
        # Set BEFORE the dispatch thread starts: _checkout_worker touches
        # these, and a task can dispatch while __init__ is still running.
        self._prestart_target = 0
        self._replenish_evt = threading.Event()
        # Materialized runtime-env package cache (per node, content-hashed).
        self._rtenv_cache_root = f"/tmp/ray_tpu_rtenv_{session}"
        os.makedirs(self._rtenv_cache_root, exist_ok=True)
        self._bundles: dict[tuple, ResourcePool] = {}
        self._bundle_state: dict[tuple, str] = {}  # PREPARED | COMMITTED
        self._task_queue: list[dict] = []
        self._queue_cv = threading.Condition(self._lock)
        # Draining (DrainRaylet analog): set by the head's drain
        # coordinator (or a preemption self-drain). A draining node
        # finishes what it has but admits no new leased pushes and
        # gossips zero availability.
        self._draining = False
        self._drain_reason: Optional[str] = None
        # Specs popped from the queue but not yet bound to a worker
        # (acquiring resources / waiting for a fork): they are neither
        # "queued" nor "running", and the drain coordinator's quiescence
        # probe must not mistake that window for an idle node.
        self._dispatch_inflight = 0
        # Demand of queued-or-acquiring tasks, not yet debited from the
        # pool: leased-push admission compares against available minus this.
        self._committed: dict[str, float] = {}
        self._shutdown = threading.Event()
        # Task state records for the state API (GetTasksInfo analog):
        # PENDING on enqueue, RUNNING on dispatch, final state from the
        # worker's batched event reports.
        self._task_records: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        self._task_records_cap = max(16, config.task_record_retention)
        # Task ids cancelled before the dispatcher picked them up (covers
        # the queue→checkout window where a task is in neither place).
        # Ordered so the bound evicts oldest-first.
        self._cancelled_tasks: "collections.OrderedDict[str, bool]" = (
            collections.OrderedDict()
        )
        # Object-serving counters (tests assert the chunked path is used).
        self._fetch_stats = {"whole": 0, "info": 0, "chunks": 0}
        # Owner-directory clients, for pushing dead-worker error
        # locations straight to the owning client (bounded LRU).
        self._owner_clients: "collections.OrderedDict[str, RpcClient]" = (
            collections.OrderedDict()
        )
        # Node reporter (reference: dashboard/modules/reporter +
        # _private/log_monitor.py). Worker stdout/stderr is captured to
        # per-worker files under log_dir (the batched worker_events tee
        # to the head stays the live-follow push path); the index below
        # keeps dead workers' logs reachable for post-mortems.
        self.log_dir = f"/tmp/ray_tpu_wlogs_{session}_{self.node_id[-8:]}"
        try:
            os.makedirs(self.log_dir, exist_ok=True)
        except OSError:
            self.log_dir = None  # degrade: workers inherit our fds
        self._worker_logs: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        # Per-worker CPU/RSS telemetry: latest snapshot + /proc cpu-tick
        # history for utilization deltas + the gauge children we have
        # exported (so dead workers' series get pruned).
        self._worker_stats: dict[str, dict] = {}
        self._cpu_prev: dict[str, tuple] = {}
        self._exported_gauges: set[tuple] = set()
        # Per-worker JAX/XLA device snapshots (util/device_telemetry),
        # shipped on the worker-events batch; exported as
        # ray_tpu_device_* gauges by the telemetry pass and pruned with
        # the worker. The exported set tracks (worker_id, device|None)
        # children so retraction is exact.
        self._device_stats: dict[str, dict] = {}
        self._exported_device: set[tuple] = set()
        # Serve gauge children created by each worker's shipped
        # observations (replica ongoing / router queue depth /
        # reconcile), retracted when the worker dies so a dead replica
        # vanishes from the federated scrape.
        self._serve_gauges: dict[str, set] = {}
        # Training goodput gauge children (the per-rank straggler
        # gauge), same retraction lifecycle as the serve gauges.
        self._train_gauges: dict[str, set] = {}
        # Last-applied worker-events batch seq per (worker_id, pid):
        # the flusher resends a batch whose ack was severed under its
        # original seq, and this table absorbs the replay (bounded,
        # insertion-ordered — the rpc_worker_events idempotence).
        self._event_seqs: "collections.OrderedDict[tuple, int]" = (
            collections.OrderedDict())
        # Remote profiler captures (state.capture_profile): manifest by
        # capture id; trace files live under log_dir and stream back
        # through read_capture_file (the log-read plane's chunked shape).
        self._captures: "collections.OrderedDict[str, dict]" = (
            collections.OrderedDict()
        )
        # One sampler at a time: a fresh=True RPC racing the telemetry
        # loop would otherwise compute cpu%% over a ~ms window (one
        # scheduler tick reads as ~1000%%) and fight over the gauge set.
        self._telemetry_lock = threading.Lock()
        self._last_sample = 0.0
        # OOM forensics: bounded index of pre-kill memory reports the
        # monitor wrote under log_dir (ray-tpu memory --node surfaces
        # them; the victim's death cause carries the path).
        self._oom_reports: list[dict] = []
        # Object-store gauge bookkeeping: evictions is cumulative in the
        # native stats — exported as a counter by delta. spill_denied is
        # agent-side cumulative (surfaced in store stats for the bench).
        self._evictions_exported = 0
        self._store_gauges_exported = False
        self._spill_denied = 0
        self._spill_restores = 0
        # Resource-view gossip (reference: ray_syncer.h:88 — nodes share
        # resource views so scheduling needn't centralize). Membership
        # (who exists / who died) still comes from the head, the GCS's
        # job; LOAD flows node<->node by anti-entropy push-pull: each
        # tick we bump our own versioned entry and exchange views with
        # `gossip_fanout` random peers; entries merge by per-origin
        # version. Consumers: rpc_peer_view (clients pick spillback
        # targets without a head RPC).
        self._cluster_view: dict[str, dict] = {}
        self._view_version = 0
        self._gossip_clients: "collections.OrderedDict[str, RpcClient]" = (
            collections.OrderedDict()
        )
        # Runtime-armed failpoint table for THIS node's workers: kept so
        # workers forked AFTER a cluster-wide arm still inherit it (they
        # are armed at registration) — without this, a chaos arm only
        # covers the workers alive at fanout time.
        self._worker_failpoints: dict[str, str] = {}
        # Same replay contract for network-chaos rules: wire-shaped rule
        # dicts (label folded in) re-applied to late-forked workers, so
        # an in-force partition isn't invisible to a worker spawned
        # mid-experiment.
        self._worker_channel_rules: list[dict] = []

        self._server = RpcServer(self, host)
        self.address = self._server.address
        # Chaos source identity: this agent's outbound clients (head
        # heartbeats, gossip, owner notifies) carry the agent address so
        # Cluster.partition's symmetric drop rules cut both directions.
        self.head.chaos_src = self.address
        self.head.call(
            "register_node", self.node_id, self.address,
            self.total_resources, self.store_path, self.labels,
        )
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()
        threading.Thread(target=self._dispatch_loop, daemon=True).start()
        # Kept joinable: stop() waits the reaper out before detaching the
        # shm store (its release_dead on a detached segment is a crash).
        self._reap_thread = threading.Thread(
            target=self._reap_loop, daemon=True)
        self._reap_thread.start()
        if config.worker_telemetry_interval_s > 0:
            threading.Thread(
                target=self._telemetry_loop, daemon=True).start()
        if config.gossip_interval_s > 0:
            threading.Thread(target=self._gossip_loop, daemon=True).start()
        if config.preemption_poll_interval_s > 0 and (
                config.preemption_signal_file
                or config.preemption_metadata_url):
            threading.Thread(
                target=self._preemption_watcher, daemon=True).start()
        # OOM protection (memory_monitor.h / worker_killing_policy.h
        # analog): watch node memory, kill the newest task's worker under
        # pressure; its refs raise OutOfMemoryError.
        from ray_tpu.cluster.memory_monitor import MemoryMonitor

        self.memory_monitor = MemoryMonitor(
            self, usage_threshold=memory_usage_threshold,
            limit_bytes=memory_limit_bytes,
        )
        self.memory_monitor.start()
        # Object-store occupancy gauges exist from boot (the telemetry
        # loop keeps them fresh; scrapes refresh them too).
        try:
            self._export_store_gauges()
        except Exception:
            pass
        # Prestart plain-env workers up to the node's CPU count (reference:
        # worker_pool.cc PrestartWorkers) so a first burst that spills onto
        # this node doesn't serialize behind interpreter cold starts.
        n_prestart = min(
            int(config.worker_prestart_per_cpu
                * self.total_resources.get("CPU", 0.0)),
            self._max_workers,
        )
        self._prestart_target = n_prestart
        if n_prestart > 0:
            threading.Thread(
                target=self._prestart_workers, args=(n_prestart,),
                daemon=True,
            ).start()
            # Keep the plain-env pool warm for the REST of the node's
            # life: actor creations consume idle workers permanently
            # (dedicated processes), so without replenishment the Nth
            # actor cold-forks again (reference worker_pool prestart is
            # likewise demand-refreshed).
            threading.Thread(
                target=self._replenish_loop, daemon=True).start()

    def _replenish_loop(self) -> None:
        while not self._shutdown.is_set():
            if not self._replenish_evt.wait(1.0):
                continue  # not signaled: only checkout demand replenishes
            if self._shutdown.is_set():
                return
            self._replenish_evt.clear()
            while not self._shutdown.is_set():
                with self._lock:
                    idle = len(self._idle.get("", []))
                    live = len([w for w in self._workers.values()
                                if w.proc.poll() is None
                                and not w.is_actor])
                    need = (idle < self._prestart_target
                            and live < self._max_workers)
                if not need:
                    break
                try:
                    w = self._spawn_worker()
                    if w.ready.wait(config.worker_start_timeout_s):
                        self._return_worker(w)
                    else:
                        break
                except (OSError, RuntimeError):
                    break  # replenish is an optimization, never fatal

    def _prestart_workers(self, n: int) -> None:
        # Deferred + serialized: a cluster booting many agents at once must
        # not fork an interpreter storm that starves node registration;
        # each fork waits for the previous worker to come up, and demand
        # that arrives meanwhile shrinks what's left to prestart.
        self._shutdown.wait(config.worker_prestart_delay_s)
        for _ in range(n):
            if self._shutdown.is_set():
                return
            with self._lock:
                idle = sum(len(v) for v in self._idle.values())
                live = len([w for w in self._workers.values()
                            if w.proc.poll() is None])
                if idle >= n or live >= self._max_workers:
                    return
            try:
                w = self._spawn_worker()
                if w.ready.wait(config.worker_start_timeout_s):
                    self._return_worker(w)
            except (OSError, RuntimeError):
                return  # prestart is an optimization, never fatal
            # Space the forks out: since workers stopped pre-importing
            # jax, forks complete in ~0.3s and N agents' prestarts
            # otherwise compress into one interpreter storm exactly when
            # a mass cluster boot needs the CPU (the slow-fork era
            # staggered this by accident).
            if self._shutdown.wait(config.worker_prestart_spacing_s):
                return

    # -- worker pool ------------------------------------------------------

    def _spawn_worker(self, env_key: str = "",
                      resolved_env: dict | None = None) -> _Worker:
        worker_id = "w-" + os.urandom(6).hex()
        env = dict(os.environ)
        env["RAY_TPU_NODE_ID"] = self.node_id
        env["RAY_TPU_WORKER_ID"] = worker_id
        # Lazy heavy imports in workers (reference: Ray workers import
        # `ray` only; torch/tf load when a task first uses them). Site
        # hooks that pre-import jax at interpreter startup (e.g. a TPU
        # plugin's sitecustomize) cost seconds per fork and serialize
        # actor creation; strip matching PYTHONPATH entries so workers
        # start in ~0.3s and tasks that use jax pay its import lazily.
        strip = config.worker_pythonpath_exclude
        if strip and env.get("PYTHONPATH"):
            keep = [p for p in env["PYTHONPATH"].split(os.pathsep)
                    if not any(s and s in p for s in strip.split(","))]
            env["PYTHONPATH"] = os.pathsep.join(keep)
        # The framework must be importable by `-m ray_tpu...` no matter
        # where the DRIVER ran from (it may have put ray_tpu on sys.path
        # itself): pin our own package root onto the worker's path.
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        prior = env.get("PYTHONPATH", "")
        if pkg_root not in prior.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + prior if prior else ""))
        cwd = None
        python = sys.executable
        if resolved_env is not None:
            # Materialize packages (content-hash cached) and bake the env
            # into the subprocess: env_vars directly, py_modules +
            # working_dir via PYTHONPATH, working_dir as cwd — the
            # interpreter picks all of it up at start, no worker-side code.
            from ray_tpu._private import runtime_env as rtenv

            recipe = rtenv.ensure_local(
                resolved_env,
                lambda k: self.head.call("kv_get", k),
                self._rtenv_cache_root,
            )
            env.update(recipe["env_vars"])
            # The framework itself may be importable only via the agent's
            # cwd; a changed cwd must not break `-m ray_tpu...` startup.
            import ray_tpu as _pkg

            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(_pkg.__file__)))
            prior = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = os.pathsep.join(
                recipe["py_paths"] + [pkg_root]
                + ([prior] if prior else [])
            )
            cwd = recipe["cwd"]
            if recipe.get("python"):
                # pip env: the worker runs under the per-env virtualenv
                # interpreter (its site-packages shadow the cluster's).
                python = recipe["python"]
        # The pool is language-aware like the reference's (worker_pool.h:80
        # keys processes by language + runtime env): a "cpp::<bin>" key
        # spawns that native binary with the same worker flags the Python
        # workerproc takes; everything after argv is shared.
        if env_key.startswith("cpp::"):
            argv = [env_key[len("cpp::"):]]
        else:
            argv = [python, "-m", "ray_tpu.cluster.workerproc"]
        # Per-worker log capture (log_monitor.py analog): the process's
        # raw stdout/stderr land in files the reporter RPCs serve; the
        # structured line tee to the head (worker_events) is unaffected.
        out_path = err_path = None
        out_f = err_f = None
        if self.log_dir is not None:
            try:
                out_path = os.path.join(self.log_dir, f"{worker_id}.out")
                err_path = os.path.join(self.log_dir, f"{worker_id}.err")
                out_f = open(out_path, "ab")
                err_f = open(err_path, "ab")
            except OSError:
                if out_f is not None:  # second open failed: no fd leak
                    out_f.close()
                out_path = err_path = out_f = err_f = None
        if out_f is None:
            stdout = (sys.stdout.fileno()
                      if hasattr(sys.stdout, "fileno") else None)
            stderr = (sys.stderr.fileno()
                      if hasattr(sys.stderr, "fileno") else None)
        else:
            stdout, stderr = out_f, err_f
        try:
            proc = subprocess.Popen(
                [
                    *argv,
                    "--head", self.head_address,
                    "--agent", self.address,
                    "--node-id", self.node_id,
                    "--store", self.store_path,
                    "--worker-id", worker_id,
                ],
                env=env,
                cwd=cwd,
                stdout=stdout,
                stderr=stderr,
            )
        finally:
            # Popen holds its own descriptors; ours would just leak.
            for f in (out_f, err_f):
                if f is not None:
                    f.close()
        w = _Worker(worker_id, proc, env_key=env_key)
        with self._lock:
            self._workers[worker_id] = w
            if out_path is not None:
                self._worker_logs[worker_id] = {
                    "worker_id": worker_id,
                    "node_id": self.node_id,
                    "pid": proc.pid,
                    "stdout_path": out_path,
                    "stderr_path": err_path,
                    "started_at": w.started_at,
                    "ended_at": None,
                }
                while len(self._worker_logs) > config.worker_log_retention:
                    old = self._worker_logs.popitem(last=False)[1]
                    for p in (old["stdout_path"], old["stderr_path"]):
                        try:
                            os.unlink(p)
                        except OSError:
                            pass
        return w

    def rpc_register_worker(self, worker_id, address, client_id=None):
        with self._lock:
            w = self._workers.get(worker_id)
            if w is None:
                return False
            w.address = address
            w.client_id = client_id  # its holder id in the head's ref table
            w.client = RpcClient(address)
            w.client.chaos_src = self.address
            armed = dict(self._worker_failpoints)
            chan_rules = list(self._worker_channel_rules)
        if armed:
            # Late-forked workers inherit the runtime-armed table —
            # BEFORE ready.set(), so no task can dispatch to a
            # not-yet-armed worker.
            try:
                w.client.call("set_failpoints", armed, timeout=5.0)
            except Exception:
                pass
        if chan_rules:
            try:
                w.client.call(
                    "set_channel_chaos", chan_rules, "", timeout=5.0)
            except Exception:
                pass
        w.ready.set()
        return True

    def _checkout_worker(self, timeout: float | None = None,
                         env_key: str = "",
                         resolved_env: dict | None = None,
                         dedicated: bool = False) -> _Worker:
        """Idle worker of the SAME runtime env, or a fresh one spawned
        into it (lease grant, ``PopWorker`` analog). ``dedicated`` (actor
        creation) bypasses the pool cap: an actor keeps its process for
        life, so counting it against the task pool would let N long-lived
        actors starve every future task on the node — the reference's
        worker pool likewise caps only poolable workers."""
        if timeout is None:
            timeout = config.worker_start_timeout_s
        with self._lock:
            pool = self._idle.get(env_key)
            if pool:
                w = pool.pop()
                if dedicated and env_key == "":
                    # The actor keeps this process for life: top the
                    # plain pool back up in the background.
                    self._replenish_evt.set()
                return w
            if env_key == "":
                self._replenish_evt.set()  # pool empty: warm it for next
            n_live = len([w for w in self._workers.values()
                          if w.proc.poll() is None and not w.is_actor])
            can_spawn = dedicated or n_live < self._max_workers
            victim = None
            if not can_spawn:
                # At capacity with nothing idle in THIS env: retire an
                # idle worker of another env to make room — otherwise a
                # node whose slots filled with (now idle) plain workers
                # could never serve a runtime_env task at all.
                victim = next(
                    (w for key, lst in self._idle.items()
                     if key != env_key for w in lst),
                    None,
                )
                if victim is not None:
                    self._idle[victim.env_key].remove(victim)
                    self._workers.pop(victim.worker_id, None)
                    can_spawn = True
        if victim is not None:
            victim.proc.kill()
            if victim.client_id:
                try:
                    self.head.call("ref_client_dead", victim.client_id)
                except Exception:
                    pass
            try:
                self.store.release_dead(victim.proc.pid)
            except Exception:
                pass
        if can_spawn:
            w = self._spawn_worker(env_key, resolved_env)
        else:
            # Every slot is BUSY: wait for one of this env's workers to
            # come back (or for capacity to free via task turnover).
            deadline = time.monotonic() + timeout
            while True:
                with self._lock:
                    pool = self._idle.get(env_key)
                    if pool:
                        w = pool.pop()
                        break
                    n_live = len([w_ for w_ in self._workers.values()
                                  if w_.proc.poll() is None
                                  and not w_.is_actor])
                    if n_live < self._max_workers:
                        can_spawn = True
                        break
                if time.monotonic() > deadline:
                    raise TimeoutError("no worker became available")
                time.sleep(0.005)
            if can_spawn:
                w = self._spawn_worker(env_key, resolved_env)
        if not w.ready.wait(timeout):
            raise TimeoutError(f"worker {w.worker_id} failed to start")
        return w

    def _return_worker(self, w: _Worker):
        with self._lock:
            if w.proc.poll() is None and not w.is_actor:
                w.current_task = None
                self._idle.setdefault(w.env_key, []).append(w)

    # -- task dispatch ----------------------------------------------------

    def rpc_submit_task(self, spec: dict):  # idempotent
        """Enqueue a task; the dispatcher leases a worker when resources
        allow. Returns immediately (results flow through the store).

        Idempotent under the task model's own contract: a replayed
        plain-task enqueue re-executes a task lineage recovery is
        allowed to re-run anyway (results land by oid, last-write-
        wins), and a replayed ACTOR push dedups at the actor's single
        worker (``_is_duplicate_push`` — exactly-once per
        incarnation)."""
        self._requeue(spec)
        return True

    def _requeue(self, spec: dict) -> None:
        """The one queue-admission sequence (record + commit + enqueue +
        notify) — submit, checkout-timeout retry, and dispatch-failure
        retry must all account identically."""
        self._record_task(spec, "PENDING")
        with self._queue_cv:
            self._commit_locked(spec)
            self._task_queue.append(spec)
            self._queue_cv.notify()

    def rpc_submit_tasks(self, specs: list):
        """Head-placed batch enqueue: one RPC, one queue notify."""
        for spec in specs:
            self._record_task(spec, "PENDING")
        with self._queue_cv:
            for spec in specs:
                self._commit_locked(spec)
            self._task_queue.extend(specs)
            self._queue_cv.notify()
        return True

    def rpc_submit_tasks_leased(self, specs: list):
        """Direct (head-bypassing) submission under a client-held
        scheduling-key lease — the decentralized half of lease pipelining
        (reference: leased-worker task pushes, direct_task_transport.cc).
        This node is NOT obligated to accept: a spec is admitted only if
        its demand fits the node's UNCOMMITTED capacity (available minus
        everything already queued), so a leased burst can never pile up
        behind running tasks while other nodes sit idle — overflow spills
        back through the head, which still balances the cluster. Returns
        the list of REJECTED indices; the client reschedules those through
        the head and drops its lease."""
        failpoints.hit("agent.lease.push")
        rejected = []
        accepted = []
        with self._queue_cv:
            if self._draining:
                # A draining node takes no new work: the client's leased
                # burst spills back through the head, which excludes us.
                return list(range(len(specs)))
            avail = self.pool.available()
            for k, v in self._committed.items():
                avail[k] = avail.get(k, 0.0) - v
            for i, spec in enumerate(specs):
                demand = spec["demand"]
                if all(avail.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        avail[k] = avail.get(k, 0.0) - v
                    self._commit_locked(spec)
                    accepted.append(spec)
                else:
                    rejected.append(i)
            for spec in accepted:
                # Record BEFORE the dispatcher can see the spec (the lock
                # is reentrant): a fast task's RUNNING/FINISHED must not
                # be overwritten by a late PENDING.
                self._record_task(spec, "PENDING")
            self._task_queue.extend(accepted)
            self._queue_cv.notify()
        return rejected

    # -- queued-demand accounting (admission control for leased pushes) ----

    def _commit_locked(self, spec: dict) -> None:
        """Caller holds self._lock. PG tasks draw on bundle capacity (carved
        out of the pool at prepare time), not on free node capacity."""
        if spec.get("pg_id") is not None:
            return
        for k, v in spec.get("demand", {}).items():
            self._committed[k] = self._committed.get(k, 0.0) + v

    def _uncommit(self, spec: dict) -> None:
        """After the dispatcher's acquire resolves (either way), the demand
        is reflected in (or irrelevant to) pool availability."""
        if spec.get("pg_id") is not None:
            return
        with self._lock:
            for k, v in spec.get("demand", {}).items():
                n = self._committed.get(k, 0.0) - v
                if n <= 1e-9:
                    self._committed.pop(k, None)
                else:
                    self._committed[k] = n

    # -- task state records (state API) -----------------------------------

    def _task_key(self, spec: dict) -> str:
        return spec.get("task_id") or spec.get("oids", ["?"])[0]

    def _record_task(self, spec: dict, state: str):
        rec = {
            "task_id": self._task_key(spec),
            "name": spec.get("fname") or spec.get("method")
            or spec.get("class_name", "task"),
            "type": "ACTOR_CREATION_TASK" if spec.get("actor_create")
            else "NORMAL_TASK",
            "state": state,
            "submitted_at": time.time(),
            "start_time": None,
            "end_time": None,
            "error": None,
        }
        with self._lock:
            old = self._task_records.get(rec["task_id"])
            if old is not None:
                if state in ("PENDING", "RUNNING") and \
                        old.get("state") in ("FINISHED", "FAILED",
                                             "CANCELLED"):
                    # A duplicate delivery of an already-settled task
                    # (retried push whose first reply was lost) must not
                    # regress the terminal record: the duplicate will be
                    # refused at the worker and no further event comes.
                    return
                old["state"] = state
                return
            if len(self._task_records) >= self._task_records_cap:
                self._task_records.popitem(last=False)
                self._count_task_record_eviction()
            self._task_records[rec["task_id"]] = rec

    def _count_task_record_eviction(self) -> None:
        """One tick per record the bounded ring pushed out — a 100k-task
        burst keeps agent RSS flat and the eviction rate visible."""
        from ray_tpu.util import metrics as _metrics

        try:
            _metrics.TASK_RECORDS_EVICTED.inc(
                tags={"node_id": self.node_id})
        except Exception:
            pass

    def rpc_worker_events(self, worker_id, pid, task_events,  # idempotent
                          log_lines, spans=None, device=None, serve=None,
                          train=None, seq=None, dropped=None):
        """Batched observability report from a worker: authoritative task
        records (with timings/outcome + per-phase wall-ns), captured
        stdout/stderr lines, finished tracing spans (forwarded to the
        head's span store), an optional device-telemetry snapshot,
        serve request-path observations, and training goodput
        observations (both replayed into THIS registry — the one the
        federated scrape sees; worker registries are never scraped).

        Idempotent per (worker, pid, seq): the flusher resends a batch
        whose reply was lost under its original sequence number, and
        the replay is absorbed here — without the dedup, a severed ack
        double-counted every serve/goodput observation in the batch
        (the exact-count planes' cross-check benches are built to
        catch precisely that)."""
        failpoints.hit("agent.worker_events.upload")
        if self._is_duplicate_event_batch(worker_id, pid, seq):
            return True
        if serve:
            try:
                from ray_tpu.serve import _observability as _serve_obs

                keys = _serve_obs.apply_events(
                    serve, node_id=self.node_id, worker=worker_id)
                if keys:
                    with self._lock:
                        self._serve_gauges.setdefault(
                            worker_id, set()).update(keys)
            except Exception:
                pass  # observability must never fail the event upload
        if train:
            try:
                from ray_tpu.util import goodput as _goodput

                keys = _goodput.apply_events(
                    train, node_id=self.node_id, worker=worker_id)
                if keys:
                    with self._lock:
                        self._train_gauges.setdefault(
                            worker_id, set()).update(keys)
            except Exception:
                pass
        if task_events:
            # Feed the phase histogram so p50/p99 per phase is
            # scrapeable without the state API (one observe per phase
            # per finished task; tag cardinality is bounded by the
            # three phase names).
            from ray_tpu.util import metrics as _metrics

            for rec in task_events:
                for phase, ns in (rec.get("phases") or {}).items():
                    try:
                        _metrics.TASK_PHASE_SECONDS.observe(
                            ns / 1e9,
                            tags={"node_id": self.node_id, "phase": phase})
                    except Exception:
                        pass
        with self._lock:
            if device is not None:
                self._device_stats[worker_id] = device
            for rec in task_events:
                old = self._task_records.get(rec["task_id"])
                if old is not None and rec.get("submitted_at") is None:
                    # The agent saw the submit; the worker only saw the run.
                    rec["submitted_at"] = old.get("submitted_at")
                if len(self._task_records) >= self._task_records_cap:
                    self._task_records.popitem(last=False)
                    self._count_task_record_eviction()
                self._task_records[rec["task_id"]] = rec
        if log_lines:
            try:
                self.head.call(
                    "worker_logs", self.node_id, pid, log_lines)
            except Exception:
                pass  # head restarting/unreachable: logs are best-effort
        if spans or dropped:
            # Node-attributed so the head's trace assembly can apply
            # this node's clock offset to the batch; the truncation
            # count rides along (worker registries are never scraped,
            # so a clipped span buffer is only visible via this path).
            try:
                self.head.call(
                    "report_spans", spans or [], self.node_id,
                    dropped=dropped or 0)
            except Exception:
                pass
        failed = [r for r in task_events if r.get("state") == "FAILED"]
        if failed:
            # Error feed (reference: error_info pubsub to the driver).
            try:
                self.head.call("publish", "ERRORS", self.node_id, {
                    "node_id": self.node_id, "pid": pid,
                    "errors": [
                        {"task_id": r["task_id"], "name": r.get("name"),
                         "error": r.get("error")} for r in failed
                    ],
                })
            except Exception:
                pass
        return True

    def _is_duplicate_event_batch(self, worker_id, pid, seq) -> bool:
        """Record-and-test a worker event batch's sequence number (the
        replay-absorb half of rpc_worker_events' idempotence). Keyed by
        (worker_id, pid) so a restarted worker's fresh numbering never
        collides with its previous incarnation's."""
        if seq is None:
            return False  # legacy/probe caller: no dedup contract
        key = (worker_id, pid)
        with self._lock:
            last = self._event_seqs.get(key)
            if last is not None and seq <= last:
                return True
            self._event_seqs[key] = seq
            self._event_seqs.move_to_end(key)
            while len(self._event_seqs) > 4096:
                self._event_seqs.popitem(last=False)
        return False

    def rpc_list_task_records(self, limit: int = 1000):
        with self._lock:
            return [dict(r) for r in list(self._task_records.values())[-limit:]]

    def _dispatch_loop(self):
        while not self._shutdown.is_set():
            with self._queue_cv:
                while not self._task_queue and not self._shutdown.is_set():
                    self._queue_cv.wait(0.5)
                if self._shutdown.is_set():
                    return
                spec = self._task_queue.pop(0)
                self._dispatch_inflight += 1
            threading.Thread(
                target=self._dispatch_tracked, args=(spec,), daemon=True
            ).start()

    def _dispatch_tracked(self, spec: dict):
        try:
            self._dispatch_one(spec)
        finally:
            with self._lock:
                self._dispatch_inflight -= 1

    def _bundle_pool(self, spec) -> Optional[ResourcePool]:
        pg_id, idx = spec.get("pg_id"), spec.get("bundle_index", -1)
        if pg_id is None:
            return None
        with self._lock:
            if idx >= 0:
                return self._bundles.get((pg_id, idx))
            for (p, _i), pool in self._bundles.items():
                if p == pg_id and pool.feasible(spec.get("demand", {})):
                    return pool
        return None

    def _consume_cancel(self, task_id) -> bool:
        with self._lock:
            if task_id is not None and task_id in self._cancelled_tasks:
                self._cancelled_tasks.pop(task_id, None)
                return True
        return False

    def _dispatch_one(self, spec: dict):
        if self._consume_cancel(spec.get("task_id")):
            self._uncommit(spec)
            self._cancel_spec(spec)
            return
        demand = spec.get("demand", {})
        pool = self.pool
        if spec.get("pg_id") is not None:
            deadline = time.monotonic() + 60.0
            while True:
                bp = self._bundle_pool(spec)
                if bp is not None and bp.try_acquire(demand):
                    pool = bp
                    acquired = True
                    break
                if time.monotonic() > deadline:
                    self._fail_task(spec, "placement group bundle unavailable")
                    return
                time.sleep(0.01)
        else:
            acquired = pool.acquire(demand, timeout=300.0)
            self._uncommit(spec)  # demand now reflected in pool (or failed)
        if not acquired:
            self._fail_task(spec, f"resources {demand} unavailable")
            return
        rtenv = spec.get("runtime_env")
        env_key = (rtenv or {}).get("env_key", "")
        if spec.get("lang") == "cpp":
            bin_path = spec.get("cpp_worker_bin") or config.cpp_worker_bin
            if not bin_path or not os.path.exists(bin_path):
                pool.release(demand)
                self._fail_task(
                    spec,
                    "no C++ worker binary for this cluster (set "
                    "RAY_TPU_CPP_WORKER_BIN or pass worker_bin= to "
                    f"cpp_function; got {bin_path!r})",
                )
                return
            env_key = "cpp::" + bin_path
        try:
            w = self._checkout_worker(
                env_key=env_key,
                resolved_env=rtenv,
                dedicated=bool(spec.get("actor_create")),
            )
        except (TimeoutError, RuntimeError, OSError) as e:
            pool.release(demand)
            if isinstance(e, TimeoutError) and \
                    spec.setdefault("_checkout_misses", 0) < 2:
                # No worker became available in time — transient under
                # load (interpreter cold starts on a saturated host are
                # unbounded). Requeue rather than fail: the reference's
                # lease request simply stays queued in this situation.
                spec["_checkout_misses"] += 1
                self._requeue(spec)
                return
            # RuntimeError/OSError: runtime-env materialization failed
            # (missing package, bad zip) — surfaced as the task's error,
            # matching the reference's runtime-env setup failures.
            if isinstance(e, TimeoutError):
                self._fail_task(
                    spec,
                    f"no worker became available after "
                    f"{spec.get('_checkout_misses', 0) + 1} attempts of "
                    f"{config.worker_start_timeout_s:.0f}s (node "
                    f"saturated?)")
            else:
                self._fail_task(spec, f"worker setup failed: {e}")
            return
        self._record_task(spec, "RUNNING")
        w.current_task = {
            "spec": spec, "pool": pool, "demand": demand, "released": False,
            "started_at": time.monotonic(),
        }
        # A cancel that raced the queue→checkout window parked its id in
        # the cancelled set; honor it now that the task is attributable.
        if not spec.get("actor_create") and self._consume_cancel(
                spec.get("task_id")):
            self._release_current(w)
            self._return_worker(w)
            self._cancel_spec(spec)
            return
        try:
            failpoints.hit("agent.dispatch.before_push")
            if spec.get("actor_create"):
                w.is_actor = True
                w.actor_id = spec["actor_id"]
                w.client.call("create_actor", spec)
                try:
                    self.head.call(
                        "register_actor", spec["actor_id"], self.node_id,
                        w.address, spec.get("class_name", "Actor"),
                        spec.get("name"),
                    )
                except ValueError as e:
                    # Registration refused (name conflict, or the actor was
                    # killed while starting): record the death for callers
                    # and retire the worker — it already constructed state.
                    self._release_current(w)
                    w.is_actor = False
                    w.actor_id = None
                    try:
                        self.head.call(
                            "register_actor_failed", spec["actor_id"], str(e)
                        )
                    except Exception:
                        pass
                    w.proc.kill()
            else:
                if w.client.call("push_task", spec) is False:
                    # Duplicate admission: this worker process already
                    # accepted the same task id (a retried push whose
                    # first delivery lost only its reply). The first
                    # copy owns the task's fate — just release this
                    # dispatch's lease and return the worker.
                    with self._lock:
                        self._release_current(w)
                        w.current_task = None
                    self._return_worker(w)
        except Exception as e:  # worker died between checkout and push
            # The task never STARTED on the corpse, so retrying with a
            # fresh worker is always safe (unlike a mid-execution death,
            # which _on_worker_failure handles with retry budgets). A
            # pooled worker can die in this window legitimately: its
            # agent-death watchdog fires under extreme load, the OOM
            # killer picks it, an operator kills the pid.
            # CLAIM the task atomically against the reap loop: whoever
            # pops current_task owns the spec's fate — without this, the
            # reaper could fail the refs while we requeue (spurious error
            # + duplicate execution).
            with self._lock:
                current = w.current_task
                w.current_task = None
            if current is not None and not current["released"]:
                current["released"] = True
                current["pool"].release(current["demand"])
            retries = spec.setdefault("_dispatch_retries", 0)
            if current is None:
                # The reaper claimed it first and already settled the
                # task's fate; just make sure the corpse is cleaned up.
                self._on_worker_failure(w, f"dispatch failed: {e}",
                                        requeued=True)
            elif current.get("cancelled"):
                # A force-cancel killed the worker in this very window:
                # the task's fate is TaskCancelledError, never a retry
                # (the cancel marker was consumed; a requeue would run
                # a cancelled task to completion).
                self._on_worker_failure(w, f"dispatch failed: {e}",
                                        requeued=True)
                self._cancel_spec(spec)
            elif current.get("oom_reason"):
                from ray_tpu.core.object_ref import OutOfMemoryError

                self._on_worker_failure(w, f"dispatch failed: {e}",
                                        requeued=True)
                self._store_task_error(
                    spec,
                    OutOfMemoryError(spec.get("fname", "task"),
                                     current["oom_reason"]),
                    "FAILED",
                )
            elif not spec.get("actor_create") and retries < 2:
                spec["_dispatch_retries"] = retries + 1
                self._requeue(spec)
                self._on_worker_failure(w, f"dispatch failed: {e}",
                                        requeued=True)
            else:
                self._on_worker_failure(w, f"dispatch failed: {e}")
                self._fail_task(spec, f"worker died: dispatch failed: {e}")

    @staticmethod
    def _release_current(w: _Worker):
        current = w.current_task
        if current is not None and not current["released"]:
            current["released"] = True
            current["pool"].release(current["demand"])

    def rpc_task_done(self, worker_id):  # idempotent
        """Worker finished its current task; release + return to pool.

        Replay-absorbing: a worker whose task-done ACK was severed
        retries, and the second delivery must be a no-op — without the
        guard the replay appended the worker to the idle pool TWICE,
        and the dispatcher could lease one process for two concurrent
        tasks. The claim is taken ATOMICALLY under the lock (a pure
        current_task check would race a concurrent replay still
        between the check and the idle-pool append)."""
        with self._lock:
            w = self._workers.get(worker_id)
            current = w.current_task if w is not None else None
            if w is None or current is None or current.get("_done"):
                return False  # unknown worker, or a replayed done
            current["_done"] = True  # first delivery owns the return
        self._release_current(w)
        self._return_worker(w)
        return True

    def rpc_task_blocked(self, worker_id):
        """The worker's task is blocked in get(): free its resources so
        other (possibly nested) tasks can run (raylet parity for workers
        blocked in ray.get)."""
        with self._lock:
            w = self._workers.get(worker_id)
        if w is not None:
            self._release_current(w)
        return True

    def rpc_task_unblocked(self, worker_id):
        with self._lock:
            w = self._workers.get(worker_id)
        if w is None or w.current_task is None:
            return False
        current = w.current_task
        if current["released"]:
            current["pool"].acquire(current["demand"],
                                    timeout=config.cpu_reacquire_budget_s)
            current["released"] = False
        return True

    def _end_borrows(self, spec: dict):
        """Release the task's in-flight arg borrows on its behalf (the
        worker that would normally report task end is gone)."""
        if spec.get("borrowed") and spec.get("task_id"):
            try:
                self.head.call("ref_task_end", spec["task_id"])
            except Exception:
                pass

    def _fail_task(self, spec: dict, reason: str):
        from ray_tpu.core.object_ref import TaskError

        err = TaskError(spec.get("fname", "task"), reason, reason)
        self._store_task_error(spec, err, "FAILED")

    def _cancel_spec(self, spec: dict):
        from ray_tpu.core.object_ref import TaskCancelledError

        err = TaskCancelledError(spec.get("fname", "task"))
        self._store_task_error(spec, err, "CANCELLED")

    def _store_task_error(self, spec: dict, err: Exception, state: str):
        from ray_tpu.core import serialization as ser

        self._record_task(spec, state)
        self._end_borrows(spec)
        meta, chunks = ser.serialize(err)
        owner = spec.get("owner_addr")
        for oid in spec["oids"]:
            try:
                self.store.put(oid, chunks, b"E" + meta)
            except Exception:
                continue
            try:
                self.head.call("add_location", oid, self.node_id,
                               is_error=True, owner_addr=owner or "")
            except Exception:
                # Head unreachable (partition / shutdown): the owner
                # notify below still unblocks the owner directly, and
                # the owner's lineage path recovers otherwise. A failed
                # directory report must not kill the calling thread
                # (the reap loop runs through here).
                pass
            if owner:
                # Unblock the owner's local wait directly (its get() no
                # longer long-polls the head for self-owned refs).
                try:
                    self._owner_notify(owner, oid)
                except Exception:
                    pass

    def _owner_notify(self, owner: str, oid: str) -> None:
        with self._lock:
            c = self._owner_clients.get(owner)
            if c is None:
                if len(self._owner_clients) > 256:
                    old = self._owner_clients.popitem(last=False)[1]
                    old.close()
                c = self._owner_clients[owner] = RpcClient(
                    owner, timeout=10.0)
                c.chaos_src = self.address
        c.call("owner_add_location", oid, self.node_id, self.address,
               self.store_path, True, 0, timeout=10.0)

    def rpc_cancel_task(self, task_id: str, force: bool = False):  # idempotent
        """CancelTask analog (``core_worker.proto`` CancelTask → raylet).
        Queued: dropped here, TaskCancelledError stored. Running:
        force kills the worker process (its lease/pins are reclaimed by
        the reap path); otherwise the cancel is forwarded to the worker
        for cooperative delivery. Returns True if the task was found."""
        with self._queue_cv:
            self._cancelled_tasks[task_id] = True
            while len(self._cancelled_tasks) > 10_000:
                # Oldest-first eviction: never the id just inserted.
                self._cancelled_tasks.popitem(last=False)
            for i, spec in enumerate(self._task_queue):
                if spec.get("task_id") == task_id:
                    self._task_queue.pop(i)
                    self._cancelled_tasks.pop(task_id, None)
                    break
            else:
                spec = None
        if spec is not None:
            self._uncommit(spec)
            self._cancel_spec(spec)
            return True
        with self._lock:
            target = next(
                (w for w in self._workers.values()
                 if w.current_task is not None
                 and w.current_task["spec"].get("task_id") == task_id),
                None,
            )
            if target is None:
                return False
            target.current_task["cancelled"] = True
            self._cancelled_tasks.pop(task_id, None)
            if force:
                # Kill UNDER the lock: outside it, the task could finish
                # and the worker be re-leased to an innocent task first.
                target.proc.kill()  # reap loop stores TaskCancelledError
                return True
            client = target.client
        try:
            client.call("cancel_task", task_id, False)
        except Exception:
            return False
        return True

    def kill_worker_oom(self, w: _Worker, reason: str,
                        expected_task=None) -> bool:
        """Memory-monitor kill: the task fails with OutOfMemoryError (not
        a retriable worker death), actors go through their restart
        budget. The reap loop finishes the cleanup. ``expected_task`` is
        the current_task the monitor observed when it picked the victim —
        if the worker has since finished it (and possibly taken an
        unrelated task or gone idle), the kill is aborted."""
        with self._lock:
            current = w.current_task
            if expected_task is not None and current is not expected_task:
                return False
            if current is not None:
                current["oom_reason"] = reason
            w.proc.kill()
        return True

    def write_oom_report(self, reason: str, victim: _Worker,
                         current_task=None):
        """OOM forensics: snapshot WHY the node is out of memory —
        per-worker RSS, shm store occupancy, and the top resident
        objects by owner/callsite — to a bounded JSON report under the
        agent's log dir BEFORE the kill destroys the evidence. Returns
        the report path (None when log capture is disabled); the
        victim's death cause carries it so a post-mortem
        ``ray-tpu memory --node <id>`` / ``state.get_log`` explains the
        kill instead of just reporting it."""
        if self.log_dir is None:
            return None
        import json as _json

        from ray_tpu.cluster.memory_monitor import system_memory

        used, total = system_memory()
        try:
            workers = self.rpc_worker_stats(fresh=True)
        except Exception:
            workers = []
        top_objects = []
        store_stats = {}
        try:
            # Bounded scan: the node is OUT OF MEMORY right now — a
            # capped join (may miss objects on a huge directory) beats
            # deferring the kill while RSS keeps climbing.
            rep = self.rpc_object_store_stats(max_objects=256)
            store_stats = rep.get("stats", {})
            top_objects = (rep.get("objects") or [])[:20]
        except Exception:
            pass
        spec = (current_task or {}).get("spec") or {}
        ts = time.time()
        report = {
            "ts": round(ts, 3),
            "node_id": self.node_id,
            "reason": reason,
            "victim": {
                "worker_id": victim.worker_id,
                "pid": victim.proc.pid,
                "is_actor": victim.is_actor,
                "actor_id": victim.actor_id,
                "task": spec.get("fname") or spec.get("method")
                or spec.get("class_name"),
                "task_id": spec.get("task_id"),
            },
            "system_memory": {"used_bytes": used, "total_bytes": total},
            "workers": [
                {"worker_id": s.get("worker_id"), "pid": s.get("pid"),
                 "rss_bytes": s.get("rss_bytes"),
                 "is_actor": s.get("is_actor")}
                for s in workers
            ],
            "object_store": store_stats,
            "top_objects": top_objects,
        }
        path = os.path.join(
            self.log_dir,
            f"oom_report_{victim.worker_id}_{int(ts * 1000)}.json")
        try:
            with open(path, "w") as f:
                _json.dump(report, f, indent=1, default=str)
        except OSError:
            return None
        with self._lock:
            self._oom_reports.append({
                "path": path, "ts": round(ts, 3), "reason": reason,
                "worker_id": victim.worker_id,
            })
            # Bounded like the capture index — evicted entries take
            # their FILES with them (sustained pressure churns victims;
            # the index trim alone would grow log_dir without bound).
            evicted, self._oom_reports = (
                self._oom_reports[:-16], self._oom_reports[-16:])
        for old in evicted:
            try:
                os.unlink(old["path"])
            except OSError:
                pass
        return path

    def discard_oom_report(self, path: str) -> None:
        """The kill this report was written for never landed (the
        victim's task ended meanwhile): drop the orphan — no death
        cause references it."""
        with self._lock:
            self._oom_reports = [r for r in self._oom_reports
                                 if r.get("path") != path]
        try:
            os.unlink(path)
        except OSError:
            pass

    def record_oom_kill(self, cause: str, victim: _Worker,
                        current_task=None, report_path=None):
        """An OOM kill actually happened: bump the per-node counter
        (visible in /metrics/cluster via federation) and emit a
        structured NODES event in the drain-event shape, so OOM kills
        surface on the control plane, not just in the victim's stderr."""
        from ray_tpu.util import metrics as _metrics

        try:
            _metrics.OOM_KILLS_TOTAL.inc(tags={"node_id": self.node_id})
        except Exception:
            pass
        spec = (current_task or {}).get("spec") or {}
        try:
            self.head.call("publish", "NODES", self.node_id, {
                "node_id": self.node_id,
                "state": "OOM_KILL",
                "reason": cause,
                "worker_id": victim.worker_id,
                "task": spec.get("fname") or spec.get("method")
                or spec.get("class_name"),
                "report_path": report_path,
            })
        except Exception:
            pass  # head restarting: the kill itself is not best-effort

    def _on_worker_failure(self, w: _Worker, cause: str,
                           requeued: bool = False):
        """Clean up a dead worker. ``requeued``: the caller already put
        the task back on the queue (pre-start death), so its refs must
        NOT be failed here."""
        with self._lock:
            self._workers.pop(w.worker_id, None)
            pool = self._idle.get(w.env_key)
            if pool is not None and w in pool:
                pool.remove(w)
            rec = self._worker_logs.get(w.worker_id)
            if rec is not None and rec["ended_at"] is None:
                rec["ended_at"] = time.time()
            # Latest device snapshot dies with the worker; its exported
            # gauge children are retracted on the next telemetry pass.
            self._device_stats.pop(w.worker_id, None)
            current = None if requeued else w.current_task
            w.current_task = None
        if w.proc.poll() is None:
            w.proc.kill()
            try:
                w.proc.wait(timeout=5)
            except Exception:
                pass
        # Reclaim shm pins the dead process can never release.
        try:
            self.store.release_dead(w.proc.pid)
        except Exception:
            pass
        if w.is_actor and w.actor_id:
            try:
                self.head.call("mark_actor_dead", w.actor_id, cause,
                               True, w.address)
            except Exception:
                pass
        if w.client_id:
            # The process's holder registrations die with it.
            try:
                self.head.call("ref_client_dead", w.client_id)
            except Exception:
                pass
        if current is not None:
            if not current["released"]:
                current["released"] = True
                current["pool"].release(current["demand"])
            spec = current["spec"]
            if spec.get("actor_create"):
                self._end_borrows(spec)
            elif current.get("cancelled"):
                # Force-cancel killed this worker on purpose: the result is
                # TaskCancelledError, not a retriable worker death.
                self._cancel_spec(spec)
            elif current.get("oom_reason"):
                from ray_tpu.core.object_ref import OutOfMemoryError

                self._store_task_error(
                    spec,
                    OutOfMemoryError(spec.get("fname", "task"),
                                     current["oom_reason"]),
                    "FAILED",
                )
            else:
                self._fail_task(spec, f"worker died: {cause}")  # ends borrows

    def _reap_loop(self):
        """Detect dead worker processes (WorkerPool's disconnect handling)
        and retry deletes deferred while readers held the object."""
        while not self._shutdown.wait(0.2):
            with self._lock:
                dead = [
                    w for w in self._workers.values() if w.proc.poll() is not None
                ]
                deferred = list(self._deferred_deletes)
            for w in dead:
                try:
                    self._on_worker_failure(
                        w, f"exit code {w.proc.returncode}"
                    )
                except Exception:
                    # The reap loop must survive anything one corpse's
                    # cleanup throws (chaos-partitioned head, store
                    # teardown races): a dead reaper leaks every later
                    # worker death.
                    continue
            for oid in deferred:
                if self.store.delete(oid) or not self.store.contains(oid):
                    with self._lock:
                        self._deferred_deletes.discard(oid)

    # -- actors -----------------------------------------------------------

    def rpc_kill_actor(self, actor_id, no_restart=True):
        with self._lock:
            target = next(
                (w for w in self._workers.values() if w.actor_id == actor_id),
                None,
            )
        if target is None:
            return False
        if no_restart:
            try:
                self.head.call("mark_actor_dead", actor_id,
                               "killed via ray_tpu.kill", False)
            except Exception:
                pass
            target.is_actor = False  # already marked dead; don't re-mark
            target.actor_id = None
        # With no_restart=False, the reap loop observes the death and the
        # head reconstructs within the max_restarts budget.
        target.proc.kill()
        return True

    def rpc_actor_ctor_failed(self, actor_id, cause):
        # A raising constructor is deterministic — restarting would just
        # raise again (reference restarts only on process failure).
        try:
            self.head.call("mark_actor_dead", actor_id, cause, False)
        except Exception:
            pass
        return True

    def rpc_detach_actor_worker(self, actor_id):
        """Drain-migration support: the head already owns this actor's
        state transition (RESTARTING on another node), so the OLD
        incarnation's worker is detached from its actor binding and
        killed — the reap loop then does plain worker cleanup instead of
        reporting a second, budget-consuming actor death."""
        with self._lock:
            target = next(
                (w for w in self._workers.values()
                 if w.actor_id == actor_id),
                None,
            )
            if target is None:
                return False
            target.is_actor = False
            target.actor_id = None
        target.proc.kill()
        return True

    # -- drain / preemption (node_manager.proto DrainRaylet analog) --------

    def rpc_drain_self(self, reason: str = "requested",
                       deadline_s: float | None = None):
        """The head's drain coordinator (or our own preemption watcher)
        says this node is leaving: stop admitting leased pushes; queued
        and running tasks keep going until the coordinator's deadline."""
        with self._lock:
            self._draining = True
            self._drain_reason = reason
        return True

    def rpc_drain_status(self):  # idempotent
        """Quiescence probe for the drain coordinator: queued tasks plus
        busy non-actor workers (actor processes hold their creation spec
        as current_task for life, so they never count as 'running')."""
        with self._lock:
            running = self._dispatch_inflight + sum(
                1 for w in self._workers.values()
                if w.current_task is not None and not w.is_actor
                and w.proc.poll() is None
            )
            return {
                "draining": self._draining,
                "reason": self._drain_reason,
                "queued": len(self._task_queue),
                "running": running,
            }

    def _self_drain(self, reason: str = "preemption") -> None:
        """Self-initiated drain (SIGTERM / preemption notice): ask the
        head to run the drain protocol for us — wait=False because the
        coordinator will call back into this agent (drain_self, then
        shutdown_node once quiesced)."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._drain_reason = reason
        try:
            self.head.call(
                "drain_node", self.node_id, reason, None, False,
                timeout=10.0)
        except Exception:
            # Head unreachable and the node is going away regardless:
            # local stop is the only remaining graceful option.
            self.stop()

    def _preemption_watcher(self) -> None:
        """Pluggable preemption-signal poll (the metadata-server watcher
        of cloud deployments; file-triggered in tests). Detection
        self-initiates a drain with reason="preemption" so actors migrate
        and owners get the retry-budget exemption BEFORE the VM vanishes."""
        interval = max(0.05, config.preemption_poll_interval_s)
        sig_file = config.preemption_signal_file
        url = config.preemption_metadata_url
        while not self._shutdown.wait(interval):
            with self._lock:
                if self._draining:
                    return
            if sig_file and self._signal_file_hit(sig_file):
                self._self_drain("preemption")
                return
            if url and self._metadata_preempted(url):
                self._self_drain("preemption")
                return

    def _signal_file_hit(self, path: str) -> bool:
        """The signal file preempts every node when empty, or only the
        nodes whose ids appear in its contents."""
        try:
            with open(path) as f:
                body = f.read().strip()
        except OSError:
            return False
        return body == "" or self.node_id in body

    @staticmethod
    def _metadata_preempted(url: str) -> bool:
        """GCE-shaped poll: .../instance/preempted returns "TRUE" once
        the termination notice lands."""
        import urllib.request

        try:
            req = urllib.request.Request(
                url, headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                body = resp.read().decode("utf-8", "replace").strip()
            return body.upper() in ("TRUE", "PREEMPTED", "1")
        except Exception:
            return False

    # -- placement group bundles (2PC participant) ------------------------

    def rpc_prepare_bundle(self, pg_id, bundle_index, bundle):  # idempotent
        with self._lock:
            if (pg_id, bundle_index) in self._bundles:
                # Idempotent replay: the head's prepare landed but its
                # reply was lost (severed channel / reconnect retry).
                # Acquiring again would double-reserve the node for one
                # logical bundle — exactly-once reservation means the
                # retry is an ack, not a second carve-out.
                return True
        if not self.pool.feasible(bundle):
            raise ValueError(f"bundle {bundle} infeasible on node {self.node_id}")
        if not self.pool.acquire(
                bundle, timeout=config.bundle_reserve_timeout_s):
            raise TimeoutError(f"bundle {bundle} not reservable on {self.node_id}")
        with self._lock:
            if (pg_id, bundle_index) in self._bundles:
                # Lost the race against a concurrent replay that
                # acquired first: give this acquisition back.
                self.pool.release(bundle)
                return True
            self._bundles[(pg_id, bundle_index)] = ResourcePool(bundle)
            self._bundle_state[(pg_id, bundle_index)] = "PREPARED"
        return True

    def rpc_commit_bundle(self, pg_id, bundle_index):  # idempotent
        with self._lock:
            # Idempotent: committing an already-committed (or unknown —
            # returned while the commit retried) bundle changes nothing.
            if (pg_id, bundle_index) in self._bundles:
                self._bundle_state[(pg_id, bundle_index)] = "COMMITTED"
        return True

    def rpc_bundle_table(self):
        """This node's live placement-group reservations:
        ``{"<pg_id>:<bundle_index>": state}`` (PREPARED | COMMITTED).
        The chaos soak's leak invariant joins this against the head's
        PG table — a reservation here that no live group's placement
        explains is a leaked carve-out."""
        with self._lock:
            return {
                f"{pg_id}:{bi}": state
                for (pg_id, bi), state in self._bundle_state.items()
            }

    def rpc_return_bundle(self, pg_id, bundle_index):  # idempotent
        with self._lock:
            pool = self._bundles.pop((pg_id, bundle_index), None)
            self._bundle_state.pop((pg_id, bundle_index), None)
            # Reference semantics: removing a PG kills the work running
            # in its bundles (gcs_placement_group_manager removal path).
            # Without this, returning the reservation below would
            # oversubscribe the node for as long as a straggler runs.
            # Scoped to THIS bundle: returning one bundle (a reschedule
            # rollback or a single migrated bundle's vacate) must not
            # kill a SIBLING bundle's healthy workers on the same node
            # — only any-bundle tasks (bundle_index < 0, whose pool we
            # never recorded) die with whichever bundle goes first.
            victims = [
                w for w in self._workers.values()
                if w.current_task is not None
                and w.current_task["spec"].get("pg_id") == pg_id
                and w.current_task["spec"].get(
                    "bundle_index", -1) in (-1, bundle_index)
                and w.proc.poll() is None
            ]
        for w in victims:
            w.proc.kill()  # reap loop stores the task error / actor death
        if pool is not None:
            # Return the bundle's FULL reservation. Any just-killed (or
            # killed-but-unreaped) worker's release drains into this now-
            # orphaned pool object, not the node pool, so returning the
            # total cannot double-free — while returning only
            # pool.available() would permanently leak whatever a
            # not-yet-reaped worker still held (observed: a finished tune
            # trial starving the next trial's PG).
            self.pool.release(pool.total)
        return True

    # -- node reporter: logs / stacks / telemetry --------------------------
    # (reference: dashboard/modules/reporter/reporter_agent.py and
    # _private/log_monitor.py — per-worker log files, py-spy stack
    # dumps/profiles, and per-process cpu/mem stats, served by the node.)

    def _log_record(self, worker_id: str) -> dict:
        with self._lock:
            rec = self._worker_logs.get(worker_id)
        if rec is None:
            raise ValueError(
                f"no log capture for worker {worker_id!r} on node "
                f"{self.node_id} (unknown worker, or capture disabled)")
        return rec

    @staticmethod
    def _log_path(rec: dict, stream: str) -> str:
        if stream in ("out", "stdout"):
            return rec["stdout_path"]
        if stream in ("err", "stderr"):
            return rec["stderr_path"]
        raise ValueError(f"stream must be out|err, got {stream!r}")

    def rpc_list_worker_logs(self):
        """Every worker (live and recently dead) with captured logs:
        id, pid, file paths+sizes, lifetime, actor binding."""
        with self._lock:
            recs = [dict(r) for r in self._worker_logs.values()]
            live = {
                w.worker_id: w for w in self._workers.values()
                if w.proc.poll() is None
            }
        out = []
        for rec in recs:
            w = live.get(rec["worker_id"])
            rec["alive"] = w is not None
            rec["is_actor"] = bool(w is not None and w.is_actor)
            rec["actor_id"] = w.actor_id if w is not None else None
            for stream in ("stdout", "stderr"):
                try:
                    rec[f"{stream}_bytes"] = os.path.getsize(
                        rec[f"{stream}_path"])
                except OSError:
                    rec[f"{stream}_bytes"] = 0
            out.append(rec)
        return out

    def rpc_read_worker_log(self, worker_id, stream: str = "out",
                            offset: int | None = None,
                            max_bytes: int = 1 << 20,
                            tail_lines: int | None = None):
        """One bounded read of a worker's captured stdout/stderr.
        ``tail_lines`` reads the file end (the ``ray logs`` default);
        otherwise reads [offset, offset+max_bytes) — pass the returned
        ``offset`` back to poll-follow."""
        path = self._log_path(self._log_record(worker_id), stream)
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        max_bytes = max(1, min(int(max_bytes), 8 << 20))
        if tail_lines is not None:
            start = max(0, size - max_bytes)
            try:
                with open(path, "rb") as f:
                    f.seek(start)
                    blob = f.read(max_bytes)
            except OSError:  # file evicted/unlinked between stat and read
                blob = b""
            n = int(tail_lines)
            lines = blob.decode("utf-8", "replace").splitlines()
            data = "\n".join(lines[-n:]) if n > 0 else ""
            if data:
                data += "\n"
            return {"worker_id": worker_id, "stream": stream,
                    "offset": size, "size": size, "data": data}
        start = min(max(0, int(offset or 0)), size)
        try:
            with open(path, "rb") as f:
                f.seek(start)
                blob = f.read(max_bytes)
        except OSError:
            blob = b""
        return {"worker_id": worker_id, "stream": stream,
                "offset": start + len(blob), "size": size,
                "data": blob.decode("utf-8", "replace")}

    def rpc_follow_worker_log(self, worker_id, stream: str = "out",
                              offset: int = 0, idle_timeout_s: float = 10.0,
                              poll_s: float = 0.2):
        """Server-streamed tail -f of a worker log (use with
        ``call_stream``): yields ``{"offset", "data"}`` chunks as the
        file grows, ends after the worker is gone and drained, or after
        ``idle_timeout_s`` without growth."""
        rec = self._log_record(worker_id)
        path = self._log_path(rec, stream)
        offset = max(0, int(offset))
        last_growth = time.monotonic()
        while not self._shutdown.is_set():
            try:
                size = os.path.getsize(path)
            except OSError:
                return
            if offset < size:
                with open(path, "rb") as f:
                    f.seek(offset)
                    blob = f.read(1 << 16)
                offset += len(blob)
                last_growth = time.monotonic()
                yield {"offset": offset,
                       "data": blob.decode("utf-8", "replace")}
                continue
            with self._lock:
                w = self._workers.get(worker_id)
                dead = w is None or w.proc.poll() is not None
            if dead or time.monotonic() - last_growth > idle_timeout_s:
                return
            time.sleep(poll_s)

    def _live_worker(self, worker_id) -> _Worker:
        with self._lock:
            w = self._workers.get(worker_id)
        if w is None or w.proc.poll() is not None:
            raise ValueError(
                f"no live worker {worker_id!r} on node {self.node_id}")
        if w.client is None and not w.ready.wait(5.0):
            raise ValueError(f"worker {worker_id!r} is not serving yet")
        return w

    def rpc_dump_worker_stack(self, worker_id):
        """Instantaneous all-thread stack report of one worker
        (``ray stack`` per-worker hop)."""
        return self._live_worker(worker_id).client.call(
            "dump_stack", timeout=15.0)

    def rpc_profile_worker(self, worker_id, duration_s: float = 1.0,
                           interval_s: float = 0.01):
        """Time-sampled profile of one worker (py-spy record analog);
        returns the plain-data profile from util/stack_sampler."""
        w = self._live_worker(worker_id)
        prof = w.client.call(
            "profile", float(duration_s), float(interval_s),
            timeout=float(duration_s) + 30.0)
        prof["node_id"] = self.node_id
        prof["pid"] = w.proc.pid
        return prof

    def rpc_device_stats(self, fresh: bool = False):
        """Per-worker JAX/XLA device snapshots on this node. Steady
        state comes from the workers' batched reports; ``fresh`` RPCs
        every live worker for an immediate snapshot (workers that never
        imported jax answer with a stub)."""
        with self._lock:
            live = {
                w.worker_id: w for w in self._workers.values()
                if w.proc.poll() is None
            }
            snaps = {wid: dict(s) for wid, s in self._device_stats.items()
                     if wid in live}
        if fresh:
            # Concurrent, short per-worker budget: a GIL-starved worker
            # must not serialize the poll past the head's per-agent
            # fanout timeout (which would drop this node's HEALTHY
            # snapshots along with the stuck one).
            targets = [(wid, w.client) for wid, w in live.items()
                       if w.client is not None]
            if targets:
                from concurrent.futures import ThreadPoolExecutor

                def one(item):
                    wid, client = item
                    try:
                        return wid, client.call("device_stats",
                                                timeout=3.0)
                    except Exception:
                        return wid, None

                with ThreadPoolExecutor(
                        max_workers=min(8, len(targets))) as pool:
                    for wid, snap in pool.map(one, targets):
                        if snap is not None:
                            snaps[wid] = snap
        out = []
        for wid, snap in snaps.items():
            snap["worker_id"] = wid
            snap["node_id"] = self.node_id
            out.append(snap)
        return out

    def rpc_capture_profile(self, worker_id, duration_s: float = 1.0,
                            interval_s: float = 0.01):
        """Remote profiler capture: open a timed ``jax.profiler.trace``
        window in the worker (stack-sampler fallback off-jax). The
        worker writes the trace files DIRECTLY into this node's capture
        dir (same host, shared filesystem — no trace bytes on the
        worker→agent hop); the returned manifest's files stream back to
        remote clients via read_capture_file."""
        import shutil

        w = self._live_worker(worker_id)
        base = self.log_dir
        if base is None:
            import tempfile

            base = tempfile.mkdtemp(prefix="ray_tpu_tprof_")
        cap_id = f"tprof-{worker_id}-{os.urandom(3).hex()}"
        cap_dir = os.path.join(base, cap_id)
        os.makedirs(cap_dir, exist_ok=True)
        try:
            res = w.client.call(
                "capture_profile", float(duration_s), float(interval_s),
                cap_dir, timeout=float(duration_s) + 60.0)
        except Exception:
            shutil.rmtree(cap_dir, ignore_errors=True)
            raise
        # Manifest from OUR walk of the dir, not the worker's claim —
        # read_capture_file trusts these names when joining paths.
        names = []
        for dirpath, _dirs, fnames in os.walk(cap_dir):
            for fname in fnames:
                path = os.path.join(dirpath, fname)
                try:
                    names.append({
                        "name": os.path.relpath(path, cap_dir),
                        "size": os.path.getsize(path),
                    })
                except OSError:
                    continue
        manifest = {
            "capture_id": cap_id,
            "node_id": self.node_id,
            "worker_id": worker_id,
            "kind": res.get("kind"),
            "duration_s": res.get("duration_s"),
            "files": sorted(names, key=lambda f: f["name"]),
        }
        with self._lock:
            self._captures[cap_id] = {**manifest, "dir": cap_dir}
            evict = []
            while len(self._captures) > 16:  # bound trace-dir disk use
                evict.append(self._captures.popitem(last=False)[1])
        for old in evict:
            shutil.rmtree(old["dir"], ignore_errors=True)
        return manifest

    def rpc_read_capture_file(self, capture_id, name, offset: int = 0,
                              max_bytes: int = 1 << 20):
        """One bounded read of a capture's trace file ([offset,
        offset+max_bytes)) — the same poll-follow shape as
        read_worker_log, so big TPU traces stream instead of riding one
        frame."""
        with self._lock:
            m = self._captures.get(capture_id)
        if m is None:
            raise ValueError(
                f"no capture {capture_id!r} on node {self.node_id}")
        if not any(f["name"] == name for f in m["files"]):
            raise ValueError(
                f"capture {capture_id} has no file {name!r}")
        path = os.path.join(m["dir"], name)
        start = max(0, int(offset))
        max_bytes = max(1, min(int(max_bytes), 8 << 20))
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                f.seek(start)
                blob = f.read(max_bytes)
        except OSError as e:
            # The trace file vanished mid-stream (external cleanup):
            # raising makes the client's download FAIL rather than
            # silently hand over a truncated, corrupt trace.
            raise ValueError(
                f"capture {capture_id} file {name!r} unreadable: {e}")
        return {"name": name, "offset": start + len(blob), "size": size,
                "data": blob}

    def rpc_metrics_text(self):
        """This agent process's full registry in Prometheus exposition
        format — the per-node input to the head's /metrics/cluster
        federation. Store occupancy is refreshed per scrape (it is one
        cheap native call; worker /proc sampling stays on the loop).

        Scrape-cost self-accounting: the render-time gauge is set to
        the PREVIOUS scrape's cost before rendering, so the cost of
        serving metrics is itself visible in the body — one scrape
        behind by construction (this scrape's cost can't be known
        until after the text is built)."""
        import time as _time

        from ray_tpu.util import metrics as _metrics

        try:
            self._export_store_gauges()
            _metrics.AGENT_METRICS_RENDER_SECONDS.set(
                getattr(self, "_last_metrics_render_s", 0.0),
                tags={"node_id": self.node_id})
        except Exception:
            pass
        t0 = _time.perf_counter()
        body = _metrics.prometheus_text()
        self._last_metrics_render_s = _time.perf_counter() - t0
        return body

    def rpc_has_worker(self, worker_id):
        """Routing probe for the head: does this node know the worker?"""
        with self._lock:
            w = self._workers.get(worker_id)
            return {
                "known": worker_id in self._worker_logs or w is not None,
                "live": w is not None and w.proc.poll() is None,
            }

    @staticmethod
    def _read_proc(pid: int):
        """(cpu_ticks, rss_bytes) for a pid from /proc, or None where
        /proc isn't available (telemetry degrades to disabled)."""
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                # Fields after the parenthesized comm (which may contain
                # spaces): index 11/12 are utime/stime (fields 14/15).
                parts = f.read().rsplit(b")", 1)[1].split()
            ticks = int(parts[11]) + int(parts[12])
            with open(f"/proc/{pid}/statm", "rb") as f:
                rss_pages = int(f.read().split()[1])
            return ticks, rss_pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError):
            return None

    def _sample_worker_stats(self) -> list:
        """Sample every live worker's CPU/RSS/uptime, refresh the
        Prometheus gauges (pruning dead workers' series), and cache the
        snapshot for rpc_worker_stats. Serialized, and rate-limited to
        one pass per 200ms: cpu%% needs a meaningful tick delta."""
        with self._telemetry_lock:
            return self._sample_worker_stats_locked()

    def _sample_worker_stats_locked(self) -> list:
        from ray_tpu.util import metrics as _metrics

        hz = os.sysconf("SC_CLK_TCK") or 100
        now = time.monotonic()
        if self._shutdown.is_set():
            return []  # stopping: never re-export retracted series
        if now - self._last_sample < 0.2 and self._worker_stats:
            with self._lock:
                return [dict(s) for s in self._worker_stats.values()]
        self._last_sample = now
        with self._lock:
            workers = [
                (w.worker_id, w.proc.pid, w.started_at, w.is_actor,
                 w.actor_id)
                for w in self._workers.values() if w.proc.poll() is None
            ]
        stats: dict[str, dict] = {}
        for wid, pid, started_at, is_actor, actor_id in workers:
            got = self._read_proc(pid)
            if got is None:
                continue
            ticks, rss = got
            prev = self._cpu_prev.get(wid)
            cpu = 0.0
            if prev is not None and now > prev[1]:
                cpu = max(0.0, (ticks - prev[0]) / hz / (now - prev[1])
                          * 100.0)
            self._cpu_prev[wid] = (ticks, now)
            stats[wid] = {
                "worker_id": wid,
                "node_id": self.node_id,
                "pid": pid,
                "cpu_percent": round(cpu, 2),
                "rss_bytes": rss,
                "uptime_s": round(time.time() - started_at, 2),
                "is_actor": is_actor,
                "actor_id": actor_id,
            }
        exported = set()
        for s in stats.values():
            tags = {"node_id": self.node_id, "worker_id": s["worker_id"],
                    "pid": str(s["pid"])}
            exported.add((s["worker_id"], str(s["pid"])))
            _metrics.WORKER_CPU_PERCENT.set(s["cpu_percent"], tags=tags)
            _metrics.WORKER_RSS_BYTES.set(s["rss_bytes"], tags=tags)
            _metrics.WORKER_UPTIME_SECONDS.set(s["uptime_s"], tags=tags)
        _metrics.NODE_WORKER_COUNT.set(
            len(stats), tags={"node_id": self.node_id})
        for wid, pid in self._exported_gauges - exported:
            tags = {"node_id": self.node_id, "worker_id": wid, "pid": pid}
            _metrics.WORKER_CPU_PERCENT.remove(tags=tags)
            _metrics.WORKER_RSS_BYTES.remove(tags=tags)
            _metrics.WORKER_UPTIME_SECONDS.remove(tags=tags)
            self._cpu_prev.pop(wid, None)
        # Serve gauges are keyed off THEIR OWN table, not the /proc
        # sample history: a replica that shipped gauge events and died
        # before its first telemetry sample never entered
        # _exported_gauges, but its series must still be retracted.
        # Liveness comes from the worker TABLE (not `stats`): serve
        # gauges are event-driven, so a spurious retraction on one
        # transient /proc read failure would never be re-exported for
        # an idle replica.
        live_wids = {wid for wid, *_ in workers}
        with self._lock:
            dead_serve = [wid for wid in self._serve_gauges
                          if wid not in live_wids]
            dead_train = [wid for wid in self._train_gauges
                          if wid not in live_wids]
        for wid in dead_serve:
            self._retract_serve_series(wid)
        for wid in dead_train:
            self._retract_train_series(wid)
        self._exported_gauges = exported
        self._export_device_gauges(set(stats))
        self._export_store_gauges_locked()
        with self._lock:
            self._worker_stats = stats
        return list(stats.values())

    def _export_device_gauges(self, live_workers: set) -> None:
        """Refresh the ray_tpu_device_* families from the workers' latest
        device snapshots, pruning dead workers' children (same lifecycle
        as the /proc gauges). The node-level device count is always set —
        0 is the documented stub on nodes where jax never loads."""
        from ray_tpu.util import metrics as _metrics

        with self._lock:
            for wid in list(self._device_stats):
                if wid not in live_workers:
                    del self._device_stats[wid]
            snaps = {wid: s for wid, s in self._device_stats.items()}
        exported: set[tuple] = set()
        n_devices = 0
        for wid, snap in snaps.items():
            wtags = {"node_id": self.node_id, "worker_id": wid}
            comp = snap.get("compile") or {}
            _metrics.DEVICE_JAX_COMPILES.set(
                comp.get("backend_compiles", 0), tags=wtags)
            _metrics.DEVICE_JAX_COMPILE_SECONDS.set(
                comp.get("compile_seconds", 0.0), tags=wtags)
            _metrics.DEVICE_JAX_CACHE_HITS.set(
                comp.get("cache_hits", 0), tags=wtags)
            _metrics.DEVICE_JAX_CACHE_MISSES.set(
                comp.get("cache_misses", 0), tags=wtags)
            exported.add((wid, None))
            devices = snap.get("devices") or []
            n_devices = max(n_devices, len(devices))
            for d in devices:
                dev = f"{d.get('platform', '?')}:{d.get('id', -1)}"
                dtags = {**wtags, "device": dev}
                _metrics.DEVICE_MEM_IN_USE.set(
                    d.get("bytes_in_use", 0), tags=dtags)
                _metrics.DEVICE_MEM_PEAK.set(
                    d.get("peak_bytes_in_use", 0), tags=dtags)
                _metrics.DEVICE_MEM_LIMIT.set(
                    d.get("bytes_limit", 0), tags=dtags)
                exported.add((wid, dev))
        _metrics.DEVICE_COUNT.set(
            n_devices, tags={"node_id": self.node_id})
        for wid, dev in self._exported_device - exported:
            self._retract_device_series(wid, dev)
        self._exported_device = exported

    def _retract_serve_series(self, wid: str) -> None:
        """Drop the serve gauge children a dead worker's events created
        (same lifecycle as the /proc and device gauges)."""
        with self._lock:
            keys = self._serve_gauges.pop(wid, None)
        if keys:
            try:
                from ray_tpu.serve import _observability as _serve_obs

                _serve_obs.retract_gauges(keys, self.node_id)
            except Exception:
                pass

    def _retract_train_series(self, wid: str) -> None:
        """Drop the goodput gauge children (per-rank step time) a dead
        worker's events created — a finished trial's ranks must vanish
        from the federated scrape."""
        with self._lock:
            keys = self._train_gauges.pop(wid, None)
        if keys:
            try:
                from ray_tpu.util import goodput as _goodput

                _goodput.retract_gauges(keys, self.node_id)
            except Exception:
                pass

    def _retract_device_series(self, wid: str, dev: str | None) -> None:
        """Drop one exported device-gauge child: the compile-counter
        family for ``dev is None``, the per-device memory family
        otherwise. The ONE place listing the gauge families, shared by
        the telemetry prune pass and agent-stop cleanup."""
        from ray_tpu.util import metrics as _metrics

        wtags = {"node_id": self.node_id, "worker_id": wid}
        if dev is None:
            _metrics.DEVICE_JAX_COMPILES.remove(tags=wtags)
            _metrics.DEVICE_JAX_COMPILE_SECONDS.remove(tags=wtags)
            _metrics.DEVICE_JAX_CACHE_HITS.remove(tags=wtags)
            _metrics.DEVICE_JAX_CACHE_MISSES.remove(tags=wtags)
        else:
            dtags = {**wtags, "device": dev}
            _metrics.DEVICE_MEM_IN_USE.remove(tags=dtags)
            _metrics.DEVICE_MEM_PEAK.remove(tags=dtags)
            _metrics.DEVICE_MEM_LIMIT.remove(tags=dtags)

    def _telemetry_loop(self):
        interval = config.worker_telemetry_interval_s
        while not self._shutdown.wait(interval):
            try:
                self._sample_worker_stats()
            except Exception:
                continue  # telemetry is best-effort, never fatal

    def rpc_worker_stats(self, fresh: bool = False):
        """Latest per-worker CPU/RSS/uptime snapshot (GetNodeStats
        analog); ``fresh`` forces an immediate sample pass."""
        with self._lock:
            snap = [dict(s) for s in self._worker_stats.values()]
        if fresh or not snap:
            try:
                snap = self._sample_worker_stats()
            except Exception:
                pass
        return snap

    # -- object serving ---------------------------------------------------

    def _restore_backend_for(self, uri: str):
        """The spill backend behind ``uri`` — the node's own backend
        when it matches (the common case: one cluster-wide spill_uri),
        else a cached foreign-URI backend (restore of objects spilled
        under an older config)."""
        if uri == getattr(self.spill_backend, "uri", None):
            return self.spill_backend
        with self._lock:
            be = self._restore_backends.get(uri)
            if be is None:
                from ray_tpu.cluster import spill_storage

                if len(self._restore_backends) > 8:
                    self._restore_backends.clear()
                be = self._restore_backends[uri] = \
                    spill_storage.backend_for(uri)
        return be

    def _count_restore(self) -> None:
        from ray_tpu.util import metrics as _metrics

        self._spill_restores += 1
        try:
            _metrics.SPILL_RESTORES_TOTAL.inc(
                tags={"node_id": self.node_id})
        except Exception:
            pass

    def rpc_restore_from_uri(self, oid, uri, owner=None):
        """Restore one spilled object from a (remote) spill target into
        THIS node's store — the recovery half of remote spill: the head
        routes a dead node's spilled objects here instead of letting
        lineage recompute them. Idempotent: an already-present object
        returns True without touching the target. ``owner`` (the owning
        client's directory address, when the head knows it) gets the
        new location pushed directly so self-owned gets unblock without
        a head sweep. Returns whether the object is now in this store."""
        if self.store.contains(oid):
            return True
        try:
            failpoints.hit("agent.restore.before_fetch")
            backend = self._restore_backend_for(uri)
        except Exception:
            return False
        got = backend.read(oid)
        if got is None:
            return False
        meta, data = got
        for attempt in range(4):
            try:
                # Not pinned (same contract as local spill restores):
                # the URI copy stays the durable one until the object is
                # freed, so a re-eviction only costs a re-fetch.
                self.store.put(oid, data, meta)
                break
            except Exception:
                # Store full: make room the same way a put does, then
                # retry; a restore that cannot fit gives up (the caller
                # falls back to lineage recomputation).
                if attempt == 3 or self.rpc_spill(
                        len(data) + config.spill_headroom_bytes) <= 0:
                    return False
        self._count_restore()
        if owner:
            try:
                self._owner_notify(owner, oid)
            except Exception:
                pass  # owner gone/partitioned: the head sweep resolves
        return True

    def rpc_fetch_object(self, oid):
        """Serve an object's (meta, data) to a peer in ONE frame — the
        small-object path. Large objects go through fetch_object_info +
        fetch_object_chunk (ObjectManager chunked transfer,
        ``object_manager.h:117``). Falls back to the spill file and
        best-effort restores it into the store (RestoreSpilledObjects
        analog)."""
        self._fetch_stats["whole"] += 1
        got = self.store.get(oid)
        if got is not None:
            data, meta = got
            try:
                return meta, bytes(data)
            finally:
                self.store.release(oid)
        restored = self._restore_from_spill(oid)
        if restored is None:
            return None
        return restored

    def _restore_from_spill(self, oid):
        try:
            failpoints.hit("agent.restore.before_fetch")
        except failpoints.FailpointError:
            return None  # chaos: restore fails, caller degrades
        got = self.spill_backend.read(oid)
        if got is None:
            return None
        meta, data = got
        try:
            # Restored copies are NOT pinned: they may be re-evicted (the
            # spill target remains the durable copy until the object is
            # freed).
            self.store.put(oid, data, meta)
        except Exception:
            pass
        self._count_restore()
        return meta, data

    def rpc_fetch_object_info(self, oid, inline_max: int = 0):
        """(meta, data_size, data_or_None) for a pull, or None if absent.
        Data rides inline when it fits in ``inline_max`` — the small-object
        fast path stays ONE round trip; only large objects pay an extra
        info RPC before chunking. Restores a spilled object into the store
        so subsequent chunk reads hit shared memory."""
        self._fetch_stats["info"] += 1
        got = self.store.get(oid)
        if got is not None:
            data, meta = got
            try:
                if len(data) <= inline_max:
                    return meta, len(data), bytes(data)
                return meta, len(data), None
            finally:
                self.store.release(oid)
        restored = self._restore_from_spill(oid)
        if restored is None:
            return None
        meta, data = restored
        if len(data) <= inline_max:
            return meta, len(data), bytes(data)
        return meta, len(data), None

    def rpc_fetch_object_stream(self, oid, size: int, chunk: int):
        """Server-streamed chunks of the object ([0, size) in ``chunk``
        slices): ONE request, N pipelined frames — removes the per-chunk
        round trip of rpc_fetch_object_chunk (the reference's object
        manager push streams chunks the same way over gRPC,
        ``object_manager.cc`` chunked push). Each chunk pins/releases
        independently so eviction/spill mid-stream degrades to the
        chunk-read fallback instead of holding a pin for the whole
        transfer."""
        self._fetch_stats["streams"] = self._fetch_stats.get("streams", 0) + 1
        for off in range(0, size, chunk):
            piece = self.rpc_fetch_object_chunk(
                oid, off, min(chunk, size - off))
            if piece is None:
                raise ObjectLostError(
                    f"object {oid[:16]}… lost mid-stream at offset {off}")
            yield piece

    def rpc_fetch_object_chunk(self, oid, offset: int, length: int):
        """One bounded chunk of the object's data ([offset, offset+length)).
        Stateless: each chunk pins/releases independently, so eviction or
        spilling mid-transfer is handled by the spill-file fallback."""
        failpoints.hit("agent.fetch.chunk")
        self._fetch_stats["chunks"] += 1
        got = self.store.get(oid)
        if got is not None:
            data, _meta = got
            try:
                return bytes(data[offset:offset + length])
            finally:
                self.store.release(oid)
        return self.spill_backend.read_range(oid, offset, length)

    def rpc_spill(self, bytes_needed: int):  # idempotent (level-triggered)
        """Move cold, unreferenced primary copies to disk until
        ``bytes_needed`` arena bytes are freed. Returns bytes freed
        (local_object_manager.h:110,122 / SpillObjects analog)."""
        # Ask the head for this node's directory slice BEFORE taking the
        # spill lock: a slow/partitioned head (60s socket) must not wedge
        # every other thread waiting to spill (memory monitor, puts
        # under pressure). Staleness is already tolerated — each
        # candidate is re-checked against the live store under the lock.
        try:
            oids = self.head.call("objects_on_node", self.node_id)
        except Exception:
            oids = []
        spilled_remote: list[str] = []
        spilled_bytes = 0
        with self._spill_lock:
            cands = []
            for oid in oids:
                try:
                    info = self.store.info(oid)
                except RuntimeError:
                    return 0  # segment unlinked under us: nothing to spill
                if info is not None and info["refcount"] == 0:
                    cands.append(
                        (info["lru_tick"], oid,
                         info["data_size"] + info["meta_size"])
                    )
            cands.sort()  # coldest first
            freed = 0
            for _tick, oid, size in cands:
                if freed >= bytes_needed:
                    break
                got = self.store.get(oid)  # pins while we copy out
                if got is None:
                    continue
                data, meta = got
                try:
                    failpoints.hit("agent.spill.before_write")
                    written = self.spill_backend.write(
                        oid, bytes(meta), bytes(data))
                except Exception:
                    # Chaos raise or target I/O error: this object stays
                    # resident; pressure continues, never corrupts.
                    self.store.release(oid)
                    continue
                self.store.release(oid)
                if self.store.evict(oid):  # despite pin: bytes now on disk
                    freed += size
                    spilled_bytes += written
                    if self.spill_backend.remote:
                        spilled_remote.append(oid)
                else:
                    self.spill_backend.delete(oid)
            if freed < bytes_needed:
                # Pressure signal: the store could not make the room a
                # put asked for (everything left is referenced/pinned) —
                # the put will raise StoreFullError after its retries.
                from ray_tpu.util import metrics as _metrics

                # A replayed spill request re-counting a denial skews a
                # stats counter, never execution state — the handler
                # stays level-triggered.  # analyze: ignore[RT002]
                self._spill_denied += 1  # analyze: ignore[RT002]
                try:
                    _metrics.OBJECT_SPILL_DENIED.inc(
                        tags={"node_id": self.node_id})
                except Exception:
                    pass
        if spilled_bytes:
            from ray_tpu.util import metrics as _metrics

            try:
                _metrics.SPILL_BYTES_TOTAL.inc(
                    spilled_bytes, tags={"node_id": self.node_id})
            except Exception:
                pass
        if spilled_remote:
            # Remote target: record the spilled copies with the head so
            # a DEAD node's objects restore from the URI instead of
            # recomputing. OUTSIDE the spill lock (a slow/partitioned
            # head must not wedge other spilling threads) and
            # best-effort — an unrecorded spill only degrades recovery
            # back to lineage recomputation.
            try:
                self.head.call("add_spilled", spilled_remote,
                               self.spill_backend.uri, timeout=10.0)
            except Exception:
                pass
        return freed

    def rpc_free_object(self, oid):  # idempotent
        """Head says nothing references this object anymore: drop the shm
        copy and any spilled copy (free-on-zero broadcast target). The
        spill lock orders this against an in-progress spill pass, so a
        spill can't recreate the target copy after we delete it."""
        with self._spill_lock:
            self.store.pin(oid, False)
            if not self.store.delete(oid) and self.store.contains(oid):
                # Actively read right now (zero-copy views alive); the reap
                # loop retries until readers release.
                with self._lock:
                    self._deferred_deletes.add(oid)
            self.spill_backend.delete(oid)
        return True

    def rpc_delete_object(self, oid):
        self.store.delete(oid)
        self.spill_backend.delete(oid)
        try:
            self.head.call("remove_location", oid, self.node_id)
        except Exception:
            pass
        return True

    def rpc_delete_spilled(self, oid, uri):  # idempotent
        """Drop one object from a spill target this node can reach (the
        head's free fanout for a DEAD node's remote-spilled copy — the
        spiller is gone, so any live node does the delete)."""
        try:
            return self._restore_backend_for(uri).delete(oid)
        except Exception:
            return False

    def rpc_store_stats(self):
        stats = self.store.stats()
        try:
            # With a shared remote spill target every node reports the
            # TARGET's totals (the pool is cluster-wide by design);
            # node-local spill dirs keep the per-node meaning.
            sp = self.spill_backend.stats()
            stats["spilled_objects"] = sp["objects"]
            stats["spilled_bytes"] = sp["bytes"]
        except OSError:
            stats["spilled_objects"] = 0
            stats["spilled_bytes"] = 0
        stats["spill_denied"] = self._spill_denied
        stats["spill_restores"] = self._spill_restores
        return stats

    def _object_attr(self, oid: str) -> dict:
        """The put-time attribution embedded in a sealed object's store
        meta ({} when absent — pre-attribution writers, error markers)."""
        from ray_tpu.core import serialization as ser

        got = self.store.get(oid)
        if got is None:
            return {}
        _data, meta = got
        try:
            return ser.meta_field(meta[1:], "attr") or {}
        except Exception:
            return {}
        finally:
            self.store.release(oid)

    def rpc_object_store_stats(self, oids=None,
                               include_objects: bool = True,
                               max_objects: int | None = None):
        """Memory-observability report for this node: shm ``stats()``
        joined with per-key ``info()`` (size/refcount/pinned) and the
        attribution riding each entry's meta, plus the OOM-report index.
        ``oids`` is normally the head's directory slice for this node
        (the store keys are digests, so the oid list comes from the
        directory); None = ask the head ourselves. ``max_objects``
        bounds the per-key scan for latency-sensitive callers (the
        pre-kill OOM snapshot) — a capped scan may miss objects."""
        with self._lock:
            reports = [dict(r) for r in self._oom_reports]
        report = {"node_id": self.node_id, "ts": time.time(),
                  "stats": self.rpc_store_stats(),
                  "oom_reports": reports}
        if not include_objects:
            return report
        if oids is None:
            try:
                oids = self.head.call("objects_on_node", self.node_id,
                                      timeout=5.0)
            except Exception:
                oids = []
        objs = []
        now = time.time()
        if max_objects is not None:
            oids = list(oids)[:max_objects]
        for oid in oids:
            try:
                info = self.store.info(oid)
            except RuntimeError:
                break  # segment unlinked under us: stats-only report
            if info is None:
                continue  # freed/spilled since the directory snapshot
            attr = self._object_attr(oid)
            created = attr.get("created_at")
            objs.append({
                "object_id": oid,
                "size": info["data_size"] + info["meta_size"],
                "refcount": info["refcount"],
                "pinned": info["pinned"],
                "sealed": True,
                "owner": attr.get("owner", ""),
                "task": attr.get("task", ""),
                "callsite": attr.get("callsite", ""),
                "age_s": round(now - created, 3) if created else None,
            })
        objs.sort(key=lambda r: r["size"], reverse=True)
        report["objects"] = objs
        return report

    def _export_store_gauges(self):
        with self._telemetry_lock:
            self._export_store_gauges_locked()

    def _export_store_gauges_locked(self):
        """Refresh the per-node object-store gauge family (used/capacity/
        objects + the eviction counter by delta). Same lifecycle as the
        worker gauges: the stop path retracts the node's series."""
        from ray_tpu.util import metrics as _metrics

        if self._shutdown.is_set():
            return  # stopping: never re-export retracted series
        try:
            st = self.rpc_store_stats()
        except RuntimeError:
            return  # segment unlinked under us
        tags = {"node_id": self.node_id}
        _metrics.OBJECT_STORE_BYTES_USED.set(st["used"], tags=tags)
        _metrics.OBJECT_STORE_BYTES_CAPACITY.set(st["capacity"], tags=tags)
        _metrics.OBJECT_STORE_OBJECTS.set(st["num_objects"], tags=tags)
        delta = st["num_evictions"] - self._evictions_exported
        if delta > 0:
            _metrics.OBJECT_STORE_EVICTIONS.inc(delta, tags=tags)
        self._evictions_exported = st["num_evictions"]
        self._store_gauges_exported = True

    # -- lifecycle --------------------------------------------------------

    # -- resource-view gossip ----------------------------------------------

    def _my_view_entry(self) -> dict:
        with self._lock:
            qdepth = len(self._task_queue)
            self._view_version += 1
            version = self._view_version
            draining = self._draining
        return {
            # A draining node gossips zero availability so no peer picks
            # it as a spillback target (leased admission rejects anyway).
            "available": {} if draining else dict(self.pool.available()),
            "queue": qdepth,
            "version": version,
            "address": self.address,
            "ts": time.time(),
        }

    def _merge_view(self, theirs: dict) -> None:
        with self._lock:
            for nid, entry in (theirs or {}).items():
                if nid == self.node_id:
                    continue  # we are authoritative for ourselves
                cur = self._cluster_view.get(nid)
                if cur is None or entry.get("version", 0) > \
                        cur.get("version", 0):
                    self._cluster_view[nid] = entry

    def rpc_gossip(self, their_view: dict) -> dict:  # idempotent
        """Push-pull anti-entropy exchange: merge the caller's view,
        return ours (ray_syncer.h bidirectional sync analog)."""
        self._merge_view(their_view)
        with self._lock:
            return dict(self._cluster_view)

    def rpc_peer_view(self) -> dict:
        """The gossiped cluster load view, for client-side spillback
        target selection (no head involved)."""
        with self._lock:
            return dict(self._cluster_view)

    def _gossip_client(self, address: str) -> RpcClient:
        with self._lock:
            c = self._gossip_clients.get(address)
            if c is None:
                if len(self._gossip_clients) > 128:
                    self._gossip_clients.popitem(last=False)[1].close()
                c = self._gossip_clients[address] = RpcClient(
                    address, timeout=10.0)
                c.chaos_src = self.address
            return c

    def _gossip_loop(self):
        import random

        tick = 0
        interval = config.gossip_interval_s
        while not self._shutdown.wait(interval):
            tick += 1
            # Adaptive cadence: anti-entropy converges in O(log n) rounds
            # regardless of interval, so large clusters don't need a
            # faster drum — but n agents x fanout at a fixed 0.5s means
            # O(n) cluster-wide RPCs/s, which measurably drags small
            # shared-core deployments (and the 1-core CI box). Stretch
            # the interval with peer count; freshness consumers gate on
            # entry ts anyway.
            with self._lock:
                n_peers = max(0, len(self._cluster_view) - 1)  # minus self
            # Capped stretch: entries must stay fresher than the
            # spillback consumer's staleness gate (client.py
            # _spill_to_peers, 10s) even after O(log n) propagation hops
            # — unbounded growth would silently disable peer spillback
            # at exactly the scale gossip exists for.
            interval = config.gossip_interval_s * min(
                8.0, max(1.0, n_peers / 4.0))
            mine = self._my_view_entry()
            with self._lock:
                self._cluster_view[self.node_id] = mine
            if tick % max(1, config.gossip_membership_every) == 1:
                # Membership from the head (its job): learn joins, drop
                # nodes it declared dead.
                try:
                    nodes = self.head.call("nodes", timeout=5.0)
                    alive = {n["NodeID"]: n["Address"]
                             for n in nodes if n["Alive"]}
                    with self._lock:
                        for nid, addr in alive.items():
                            if nid != self.node_id and \
                                    nid not in self._cluster_view:
                                self._cluster_view[nid] = {
                                    "available": {}, "queue": 0,
                                    "version": 0, "address": addr,
                                    "ts": 0.0,
                                }
                        for nid in list(self._cluster_view):
                            if nid != self.node_id and nid not in alive:
                                del self._cluster_view[nid]
                except Exception:
                    pass  # head hiccup: keep gossiping the stale view
            with self._lock:
                peers = [(nid, e["address"])
                         for nid, e in self._cluster_view.items()
                         if nid != self.node_id and e.get("address")]
                snapshot = dict(self._cluster_view)
            if not peers:
                continue
            for _nid, addr in random.sample(
                    peers, min(config.gossip_fanout, len(peers))):
                try:
                    theirs = self._gossip_client(addr).call(
                        "gossip", snapshot, timeout=5.0)
                    self._merge_view(theirs)
                except (ConnectionLost, OSError):
                    continue  # peer down: membership refresh cleans up

    def _heartbeat_loop(self):
        beats = 0
        while not self._shutdown.wait(config.heartbeat_interval_s):
            try:
                failpoints.hit("agent.heartbeat")
                resp = self.head.call(
                    "heartbeat", self.node_id, self.pool.available(),
                    timeout=5.0,
                )
                if not resp.get("ok"):
                    # Head declared us dead: actually exit (kill workers,
                    # stop serving) instead of running on as a zombie node.
                    self.stop()
                    return
                beats += 1
                if beats % max(1, config.clock_probe_every_beats) == 0:
                    self._probe_clock()
            except Exception:
                continue

    def _probe_clock(self):
        """NTP-style offset estimate against the head's clock, riding
        the heartbeat cadence: offset = ((t1-t0)+(t2-t3))/2 with rtt as
        the quality weight. The head's trace assembly shifts this node's
        span timestamps by the min-RTT-filtered median, so cross-node
        critical paths don't invert at machine clock skew. Suppressed:
        the probe must never generate spans of its own (it would recurse
        into the very plane it calibrates)."""
        from ray_tpu.util import tracing as _tracing

        try:
            with _tracing.suppressed():
                t0 = time.time()
                t1, t2 = self.head.call("clock_probe", t0, timeout=5.0)
                t3 = time.time()
                offset = ((t1 - t0) + (t2 - t3)) / 2.0
                rtt = (t3 - t0) - (t2 - t1)
                self.head.call("report_clock", self.node_id, offset,
                               rtt, timeout=5.0)
        except Exception:
            pass  # best-effort: next beat re-probes

    # -- chaos / fault-injection control plane -----------------------------

    def rpc_set_failpoints(self, specs: dict, include_workers: bool = True):
        """Arm/disarm failpoints in this agent's process and (by default)
        every live worker process on this node — including workers forked
        LATER (the armed table re-applies at worker registration)."""
        out = {"agent": failpoints.set_failpoints(specs)}
        if include_workers:
            with self._lock:
                for site, spec in (specs or {}).items():
                    if spec:
                        self._worker_failpoints[site] = spec
                    else:
                        self._worker_failpoints.pop(site, None)
            with self._lock:
                workers = [w for w in self._workers.values()
                           if w.client is not None
                           and w.proc.poll() is None]
            for w in workers:
                try:
                    out[w.worker_id] = w.client.call(
                        "set_failpoints", specs, timeout=5.0)
                except Exception as e:
                    out[w.worker_id] = {"error": repr(e)}
        return out

    def rpc_list_failpoints(self):
        """This agent's armed table plus each live worker's (the fold
        the head's list surface promises — a worker-side arm that
        errored must be visible as its absence here)."""
        out = {"agent": failpoints.list_armed()}
        with self._lock:
            workers = [(w.worker_id, w.client)
                       for w in self._workers.values()
                       if w.client is not None and w.proc.poll() is None]
        for wid, client in workers:
            try:
                out[wid] = client.call("list_failpoints", timeout=5.0)
            except Exception as e:
                out[wid] = {"error": repr(e)}
        return out

    def rpc_set_channel_chaos(self, rules: list, label: str = "",
                              include_workers: bool = True):
        n = channel_chaos.add_rule_dicts(rules, label)
        if include_workers:
            with self._lock:
                # Kept for replay at worker registration (the failpoint
                # table's contract): a worker forked mid-partition must
                # still observe the cut.
                self._worker_channel_rules.extend(
                    dict(r, label=label) if label and not r.get("label")
                    else dict(r)
                    for r in rules)
            # Workers tag their clients with THIS node's identity, so
            # node-keyed rules (partitions) genuinely cut their traffic
            # too. Best-effort: a worker mid-spawn arms nothing.
            for w in self._live_worker_clients():
                try:
                    w.call("set_channel_chaos", rules, label, timeout=5.0)
                except Exception:
                    continue
        return n

    def rpc_clear_channel_chaos(self, label: str | None = None,
                                include_workers: bool = True):
        n = channel_chaos.clear(label)
        if include_workers:
            with self._lock:
                if label is None:
                    self._worker_channel_rules = []
                else:
                    self._worker_channel_rules = [
                        r for r in self._worker_channel_rules
                        if r.get("label") != label]
            for w in self._live_worker_clients():
                try:
                    w.call("clear_channel_chaos", label, timeout=5.0)
                except Exception:
                    continue
        return n

    def _live_worker_clients(self):
        with self._lock:
            return [w.client for w in self._workers.values()
                    if w.client is not None and w.proc.poll() is None]

    def rpc_worker_addresses(self):  # idempotent (read-only)
        """Live workers' RPC server addresses. Partition group
        resolution folds these into a node's address set: traffic
        addressed DIRECTLY to a worker (cross-node actor pushes, owner
        notifies) must observe the node's cut, not just traffic to the
        agent."""
        with self._lock:
            return [w.address for w in self._workers.values()
                    if w.address and w.proc.poll() is None]

    def rpc_list_channel_chaos(self):
        return channel_chaos.describe()

    def rpc_event_stats(self):
        """Per-RPC-handler timing stats (event_stats.h analog)."""
        return self._server.handler_stats()

    def rpc_ping(self):
        return "pong"

    def rpc_shutdown_node(self):
        threading.Thread(target=self.stop, daemon=True).start()
        return True

    def close_outbound_clients(self):
        """Close this agent's outbound clients (head, gossip, owner) so
        threads blocked in a reconnect window (head client retries for
        head_reconnect_window_s) or spinning against an armed chaos rule
        observe ``_closed`` and exit NOW — a stopped or chaos-killed
        agent must not leave heartbeat/gossip threads retrying past
        teardown into the next test's cluster. Used by the graceful stop
        path and by ``Cluster.kill_node``'s ungraceful chaos path."""
        with self._lock:
            outbound = [self.head, *self._gossip_clients.values(),
                        *self._owner_clients.values()]
        for c in outbound:
            try:
                c.close()
            except Exception:
                pass

    def stop(self):
        with self._lock:
            if getattr(self, "_stopped", False):
                done = self._stop_done
            else:
                done = None
                self._stopped = True
                self._stop_done = threading.Event()
        if done is not None:
            # Another thread (e.g. the drain coordinator's shutdown_node
            # RPC) is already stopping this agent: wait it out so callers
            # get the synchronous contract — by return, the store is
            # closed/unlinked and no native call can race a new segment.
            done.wait(15.0)
            return
        try:
            self._stop_inner()
        finally:
            self._stop_done.set()

    def _stop_inner(self):
        self._shutdown.set()
        # Retract this node's telemetry series (tests run many agents per
        # process; a stopped node must not leave stale gauge children).
        try:
            from ray_tpu.util import metrics as _metrics

            # Under the telemetry lock so a sampling pass in flight
            # can't re-export a series after we retract it.
            with self._telemetry_lock:
                for wid, pid in self._exported_gauges:
                    tags = {"node_id": self.node_id, "worker_id": wid,
                            "pid": pid}
                    _metrics.WORKER_CPU_PERCENT.remove(tags=tags)
                    _metrics.WORKER_RSS_BYTES.remove(tags=tags)
                    _metrics.WORKER_UPTIME_SECONDS.remove(tags=tags)
                self._exported_gauges = set()
                _metrics.NODE_WORKER_COUNT.remove(
                    tags={"node_id": self.node_id})
                for wid, dev in self._exported_device:
                    self._retract_device_series(wid, dev)
                self._exported_device = set()
                _metrics.DEVICE_COUNT.remove(
                    tags={"node_id": self.node_id})
                # Object-store + OOM series die with the node: a dead
                # node must not keep reporting occupancy into the
                # federated scrape.
                tags = {"node_id": self.node_id}
                if self._store_gauges_exported:
                    _metrics.OBJECT_STORE_BYTES_USED.remove(tags=tags)
                    _metrics.OBJECT_STORE_BYTES_CAPACITY.remove(tags=tags)
                    _metrics.OBJECT_STORE_OBJECTS.remove(tags=tags)
                    self._store_gauges_exported = False
                _metrics.OBJECT_STORE_EVICTIONS.remove(tags=tags)
                _metrics.OBJECT_SPILL_DENIED.remove(tags=tags)
                _metrics.SPILL_BYTES_TOTAL.remove(tags=tags)
                _metrics.SPILL_RESTORES_TOTAL.remove(tags=tags)
                _metrics.OOM_KILLS_TOTAL.remove(tags=tags)
                # Serve + goodput gauge children die with the node too.
                for wid in list(self._serve_gauges):
                    self._retract_serve_series(wid)
                for wid in list(self._train_gauges):
                    self._retract_train_series(wid)
        except Exception:
            pass
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc.poll() is None:
                w.proc.kill()
        for w in workers:
            try:
                w.proc.wait(timeout=5)
            except Exception:
                pass
        self._server.stop()
        self.close_outbound_clients()
        # The reap loop may be mid-iteration on the workers just killed;
        # let it finish before the store detaches (release_dead on a
        # closed segment is guarded, but ordering keeps cleanup complete).
        try:
            self._reap_thread.join(timeout=10.0)
        except RuntimeError:
            pass  # stop() invoked from the reap thread itself
        self.store.close(unlink=True)


def main():
    import argparse
    import signal

    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--store-capacity", type=int, default=DEFAULT_STORE_CAPACITY)
    parser.add_argument("--session", default=None)
    args = parser.parse_args()
    import json

    # Standalone agents sweep dead runs' leaked shm segments before
    # allocating their own (same hygiene as cluster_utils.Cluster).
    from ray_tpu.util.shm_sweep import sweep_stale_shm

    sweep_stale_shm()
    agent = NodeAgent(
        args.head,
        num_cpus=args.num_cpus,
        resources=json.loads(args.resources),
        store_capacity=args.store_capacity,
        session=args.session,
    )
    print(f"NODE_ADDRESS={agent.address}", flush=True)

    # SIGTERM is a preemption/termination notice (spot TPU pods get one
    # seconds before the VM vanishes): self-drain so the head migrates
    # actors and owners get the retry exemption, instead of dying as a
    # crash. A second SIGTERM (or SIGINT) stops immediately.
    def _on_signal(signum, _frame):
        if signum == signal.SIGTERM and not agent._shutdown.is_set():
            with agent._lock:
                first = not agent._draining
            if first:
                threading.Thread(
                    target=agent._self_drain, args=("preemption",),
                    daemon=True,
                ).start()
                return
        threading.Thread(target=agent.stop, daemon=True).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    agent._shutdown.wait()
    agent.stop()


if __name__ == "__main__":
    main()
