"""Node memory monitor + worker killing policy (OOM protection).

Reference: ``src/ray/common/memory_monitor.h:52`` (periodic usage check
against a threshold, cgroup-aware) and
``src/ray/raylet/worker_killing_policy.h:30`` (pick a victim worker when
the node is about to OOM, preferring the newest task so the oldest —
most-progressed — work survives; killed tasks fail with an OOM-specific
error rather than taking down the whole node).

Two trigger modes:
* system threshold — used/total of the node (MemAvailable-based, cgroup
  limit respected when present) exceeds ``usage_threshold`` (default
  0.95, env ``RAY_TPU_MEMORY_USAGE_THRESHOLD``);
* worker aggregate limit — the summed RSS of this agent's workers
  exceeds ``limit_bytes`` (env ``RAY_TPU_MEMORY_LIMIT_BYTES``; unset by
  default). This is also the deterministic hook tests use.
"""

from __future__ import annotations

import os
import threading
import time

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def system_memory() -> tuple[int, int]:
    """(used_bytes, total_bytes), respecting a cgroup-v2 limit if one is
    below the machine total (containers)."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        return 0, 1
    used = total - avail
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            climit = int(raw)
            if 0 < climit < total:
                with open("/sys/fs/cgroup/memory.current") as f:
                    cused = int(f.read().strip())
                return cused, climit
    except (OSError, ValueError):
        pass
    return used, max(total, 1)


def process_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        return 0


class MemoryMonitor:
    """Watches memory and asks the agent to kill a worker on pressure.

    The victim policy (``pick_victim``) prefers, in order:
    1. the plain-task worker whose task started most recently (its lost
       progress is smallest; retriable by the owner's policy),
    2. the newest actor worker (its restart budget applies).
    Idle workers hold no task and are never victims — their memory is the
    pool's, reclaimed separately by idle cleanup.
    """

    def __init__(self, agent, *, usage_threshold: float | None = None,
                 limit_bytes: int | None = None,
                 interval_s: float | None = None):
        from ray_tpu.core.config import config

        if usage_threshold is None:
            usage_threshold = config.memory_usage_threshold
        if limit_bytes is None:
            limit_bytes = config.memory_limit_bytes or None
        self.agent = agent
        self.usage_threshold = usage_threshold
        self.limit_bytes = limit_bytes
        self.interval_s = (config.memory_monitor_interval_s
                           if interval_s is None else interval_s)
        self.kills = 0  # observability: how many OOM kills this node did
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def _loop(self):
        while not self.agent._shutdown.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:
                continue  # the monitor must never die

    # -- one check ---------------------------------------------------------

    def check_once(self) -> bool:
        """Returns True if a worker was killed this check."""
        reason = None
        if self.limit_bytes is not None:
            rss = self.workers_rss()
            if rss > self.limit_bytes:
                reason = (f"worker memory {rss >> 20} MiB exceeds the "
                          f"node limit {self.limit_bytes >> 20} MiB")
        if reason is None and self.usage_threshold < 1.0:
            used, total = system_memory()
            if used / total > self.usage_threshold:
                reason = (f"node memory usage {used / total:.0%} above "
                          f"threshold {self.usage_threshold:.0%}")
        if reason is None:
            return False
        picked = self.pick_victim()
        if picked is None:
            return False
        victim, expected_task = picked
        # OOM forensics: snapshot the memory state (per-worker RSS, shm
        # occupancy, top objects by owner/callsite) BEFORE the kill
        # destroys the evidence, and fold the report path into the death
        # cause so the victim's OutOfMemoryError explains *why*.
        report_path = None
        writer = getattr(self.agent, "write_oom_report", None)
        if writer is not None:
            try:
                report_path = writer(reason, victim, expected_task)
            except Exception:
                report_path = None
        cause = reason if report_path is None else (
            f"{reason} (memory report: {report_path})")
        if not self.agent.kill_worker_oom(victim, cause, expected_task):
            # Victim's task ended meanwhile: re-evaluate next tick, and
            # drop the report nothing will ever reference (sustained
            # pressure with fast task turnover would otherwise churn
            # orphan files every 0.25s check).
            if report_path is not None:
                discard = getattr(self.agent, "discard_oom_report", None)
                if discard is not None:
                    try:
                        discard(report_path)
                    except Exception:
                        pass
            return False
        self.kills += 1
        # Control-plane visibility: structured head event (drain-event
        # shape) + ray_tpu_oom_kills_total, only for kills that landed.
        recorder = getattr(self.agent, "record_oom_kill", None)
        if recorder is not None:
            try:
                recorder(cause, victim, expected_task, report_path)
            except Exception:
                pass
        # Give the kill time to actually release memory before the next
        # check re-fires (the reap loop runs async).
        time.sleep(0.2)
        return True

    def workers_rss(self) -> int:
        with self.agent._lock:
            pids = [w.proc.pid for w in self.agent._workers.values()
                    if w.proc.poll() is None]
        return sum(process_rss(p) for p in pids)

    def pick_victim(self):
        with self.agent._lock:
            busy = [w for w in self.agent._workers.values()
                    if w.proc.poll() is None and w.current_task is not None]
            tasks = [w for w in busy if not w.is_actor]
            pool = tasks or [w for w in busy if w.is_actor]
            if not pool:
                return None
            # Newest task = least progress lost (retriable-lifo policy).
            # Return (worker, its-observed-task) so the kill can abort if
            # the worker moved on to different work in the meantime.
            w = max(pool, key=lambda w: w.current_task.get("started_at", 0.0))
            return w, w.current_task
