"""Cluster-wide internal KV store.

Reference parity: ``ray.experimental.internal_kv`` backed by the GCS KV
table (``src/ray/gcs/gcs_server/gcs_kv_manager.h``). Here the head server
holds the table in cluster mode; the local backend holds it in-process.
This is the rendezvous substrate for collective-group bootstrap (the
NCCL-uid-via-named-actor pattern of the reference becomes
coordinator-address-via-KV, see ``ray_tpu.parallel.distributed``).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private import worker as _worker


def _internal_kv_put(key: str, value, overwrite: bool = True) -> bool:
    """Store key -> value; returns True if written."""
    return _worker.backend().kv_put(key, value, overwrite)


def _internal_kv_get(key: str):
    return _worker.backend().kv_get(key)


def _internal_kv_del(key: str) -> bool:
    return _worker.backend().kv_del(key)


def _internal_kv_list(prefix: str = "") -> list[str]:
    return _worker.backend().kv_keys(prefix)


def kv_put(key: str, value, overwrite: bool = True) -> bool:
    return _internal_kv_put(key, value, overwrite)


def kv_get(key: str, default=None):
    v = _internal_kv_get(key)
    return default if v is None else v


def kv_del(key: str) -> bool:
    return _internal_kv_del(key)


def kv_keys(prefix: str = "") -> list[str]:
    return _internal_kv_list(prefix)
