"""Experimental APIs (reference: ``python/ray/experimental/``)."""

from ray_tpu.experimental.internal_kv import (
    _internal_kv_del,
    _internal_kv_get,
    _internal_kv_list,
    _internal_kv_put,
)

__all__ = [
    "_internal_kv_put",
    "_internal_kv_get",
    "_internal_kv_del",
    "_internal_kv_list",
]
