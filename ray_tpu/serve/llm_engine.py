"""Continuous-batching LLM decode engine (the millions-of-users datapath).

The single-tenant ``generate()``-per-request serving shape recompiles or
runs a private decode loop per caller; on TPU the idiomatic XLA answer
is the opposite: ONE compiled decode step over a fixed ``[max_batch]``
state, with requests admitted into (and evicted from) the running batch
**between** steps — iteration-level scheduling. This module is that
engine, mounted as an ordinary Serve deployment callable:

* **Two compiled shapes, ever.** A fixed ``[max_batch]`` decode step
  and a fixed ``[prefill_rows, max_prompt_len]`` chunked-prefill lane
  (``models/gpt2.py`` / ``models/llama.py`` decode APIs). Per-engine
  compile counters (trace-time side effects, the ``fused_norm`` test
  idiom) prove no per-request recompile ever happens —
  ``serve_bench --llm`` asserts ``compiles == {decode: 1, prefill: 1}``
  after 10k streams.
* **Slot-indexed ring KV-cache in device memory.** Per-slot write
  cursors via ``lax.dynamic_update_slice``; the cache rides the model's
  activation dtype (bf16 — no fp32 copy) and, for Llama, the GQA
  ``n_kv_head`` layout. A finished/shed request's slot is recycled at
  the next step boundary; generations longer than the cache degrade to
  sliding-window attention instead of erroring.
* **Deadline semantics ride the PR-8 shed plumbing.** A request whose
  absolute deadline dies — queued or mid-decode — frees its slot at the
  next step boundary as a TYPED shed (``RequestShedError``,
  ``reason="decode"``, counted in ``ray_tpu_serve_shed_total``), never
  a hang; admission prefers requests by deadline slack.
* **Token streaming.** Every request is a stream of per-step token
  chunks drained by ``llm_next``/``llm_poll`` long-polls — the
  transport ``serve._private.stream_call`` (handle ``.stream()``, HTTP
  chunked transfer, the ``ray://`` proxy's server-streaming RPC) builds
  on.

Failpoints ``serve.llm.before_admit`` / ``serve.llm.before_step`` let
chaos crash, delay or hang the scheduler mid-iteration; the loop
requeues interrupted admissions (bounded retries) and fails active
streams fast after repeated step errors — fail fast, never hang.

Metric families (two-sided through ``serve/_observability``):
``ray_tpu_serve_decode_{step_seconds,batch_occupancy,ttft_seconds,
tokens_total}``.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
import warnings
from typing import Dict, List, Optional

from ray_tpu.serve import _observability as _obs
from ray_tpu.serve._observability import RequestShedError
from ray_tpu.util import failpoints
from ray_tpu.util import goodput as _goodput
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing

# How many consecutive decode-step failures fail the active streams
# (each failure already surfaced; three in a row means the step itself
# is broken, and holding streams open past that would be a hang).
_MAX_STEP_ERRORS = 3
# Abandoned-stream reap: a DONE stream nobody polls for this long is
# dropped (the bench's fire-and-forget shed probes must not accumulate).
_STREAM_TTL_S = 120.0


class _Stream:
    """One request's token stream: per-step chunks pending delivery plus
    the terminal state. ``event`` is set whenever there is something new
    to deliver (chunks or the terminal transition)."""

    __slots__ = ("pending", "done", "shed", "error", "delivered",
                 "last_poll", "event", "n_tokens")

    def __init__(self):
        self.pending: List[List[int]] = []
        self.done = False
        self.shed: Optional[str] = None
        self.error: Optional[str] = None
        self.delivered = False
        self.last_poll = time.monotonic()
        self.event = threading.Event()
        self.n_tokens = 0


class _Request:
    __slots__ = ("rid", "prompt", "max_new", "deadline_ts", "submitted",
                 "remaining", "retries", "stream", "seq", "trace_ctx",
                 "span")

    def __init__(self, rid: str, prompt: List[int], max_new: int,
                 deadline_ts: Optional[float], seq: int,
                 trace_ctx: Optional[dict] = None):
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_ts = deadline_ts
        self.submitted = time.time()
        self.remaining = max_new
        self.retries = 0
        self.stream = _Stream()
        self.seq = seq  # FIFO tiebreak for slack ordering
        # Flight-recorder state (None when the caller doesn't trace):
        # the caller's span context, and the request's OPEN phase span
        # (llm.queue -> llm.prefill -> llm.decode, exactly one open at
        # a time). Manual spans (tracing.start_span): the engine loop
        # runs on its own thread, and a request's lifecycle crosses
        # submit-thread -> loop-thread, so thread-local context
        # managers cannot carry them. Mutated only under the engine
        # lock; every terminal path closes via _finish_locked.
        self.trace_ctx = trace_ctx
        self.span: Optional[dict] = None


def _model_bundle(model: str, config, preset: str):
    """(config, init, init_cache, prefill, decode_step) for a model
    family — resolved lazily so importing this module never pulls jax."""
    if model == "gpt2":
        from ray_tpu.models import gpt2 as m

        cfg = config or (m.GPT2Config.tiny() if preset == "tiny"
                         else m.GPT2Config.small())
        return (cfg, m.gpt2_init, m.gpt2_init_cache, m.gpt2_prefill,
                m.gpt2_decode_step)
    if model == "llama":
        from ray_tpu.models import llama as m

        cfg = config or (m.LlamaConfig.tiny() if preset == "tiny"
                         else m.LlamaConfig.small())
        return (cfg, m.llama_init, m.llama_init_cache, m.llama_prefill,
                m.llama_decode_step)
    raise ValueError(f"unknown model family {model!r} (want gpt2|llama)")


class LLMEngine:
    """The deployment callable: one decode engine per replica.

    Deploy it like any Serve class::

        eng = serve.deployment(name="llm", max_concurrent_queries=64)(
            LLMEngine)
        handle = serve.run(eng.bind(model="gpt2", max_batch=32))
        for chunk in handle.stream([1, 2, 3], max_new_tokens=16):
            ...

    ``__call__``/``generate`` are the blocking request/response lane;
    ``llm_submit``/``llm_next``/``llm_poll`` are the streaming protocol
    ``stream_call`` drives.
    """

    def __init__(self, model: str = "gpt2", config=None,
                 preset: str = "tiny", seed: int = 0,
                 max_batch: int = 8, cache_len: int = 64,
                 max_prompt_len: int = 16, prefill_rows: int = 4,
                 max_new_tokens: int = 16, max_new_cap: int = 512,
                 max_queue: int = 8192, eos_token: Optional[int] = None,
                 step_throttle_s: float = 0.0,
                 deployment: Optional[str] = None):
        import jax
        import numpy as np

        if max_prompt_len > cache_len:
            raise ValueError(
                f"max_prompt_len={max_prompt_len} must fit the cache "
                f"(cache_len={cache_len})")
        self._np = np
        self._jnp = jax.numpy
        self.model = model
        self.max_batch = int(max_batch)
        self.cache_len = int(cache_len)
        self.max_prompt_len = int(max_prompt_len)
        self.prefill_rows = max(1, min(int(prefill_rows), self.max_batch))
        self.max_new_tokens = int(max_new_tokens)
        self.max_new_cap = int(max_new_cap)
        self.max_queue = int(max_queue)
        self.eos_token = eos_token
        self.step_throttle_s = float(step_throttle_s)
        # Metrics label. None = adopt the Serve deployment's name (the
        # Replica calls set_deployment_name at construction); an
        # explicit bind arg wins over the adoption.
        self._dep = deployment or "llm"
        self._dep_explicit = deployment is not None

        cfg, init, init_cache, prefill, decode = _model_bundle(
            model, config, preset)
        if model == "gpt2" and self.max_prompt_len > cfg.seq_len:
            # gpt2's learned position table bounds the prefill window;
            # fail at bind time, not per-request inside the jit.
            raise ValueError(
                f"max_prompt_len={self.max_prompt_len} exceeds the "
                f"model's position window (seq_len={cfg.seq_len})")
        self._cfg = cfg
        self.params = init(jax.random.PRNGKey(seed), cfg)
        # One scratch slot past max_batch: inactive prefill rows write
        # their pad garbage there, keeping the prefill shape fixed.
        self._cache = init_cache(cfg, self.max_batch + 1, self.cache_len)
        self._compiles = {"decode": 0, "prefill": 0}

        def step_fn(params, cache, tokens, pos):
            self._compiles["decode"] += 1  # trace-time: fires per compile
            logits, cache = decode(params, cache, tokens, pos, cfg)
            return (self._jnp.argmax(logits, axis=-1).astype(
                self._jnp.int32), cache)

        def prefill_fn(params, cache, tokens, slots, lengths):
            self._compiles["prefill"] += 1
            logits, cache = prefill(params, cache, tokens, slots,
                                    lengths, cfg)
            return (self._jnp.argmax(logits, axis=-1).astype(
                self._jnp.int32), cache)

        # Donate the cache: the engine holds the ONLY reference and the
        # step replaces it, so XLA can update in place (2x HBM saved on
        # the big buffer). CPU test runs warn that donation was unused.
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        self._step_fn = jax.jit(step_fn, donate_argnums=(1,))
        self._prefill_fn = jax.jit(prefill_fn, donate_argnums=(1,))
        # Step-anatomy cost model (round 19): a counter-free twin of
        # the decode step for xla_cost lowering — lowering _step_fn
        # itself would re-run its traced body and bump the
        # compile-counter invariant serve_bench asserts on. Lazy and
        # opt-in via step_cost(): the extra XLA compile is not free.
        self._cost_fn = jax.jit(
            lambda params, cache, tokens, pos: decode(
                params, cache, tokens, pos, cfg))
        self._step_cost: Optional[dict] = None
        self._step_cost_flops = 0.0

        self._tokens = np.zeros(self.max_batch + 1, np.int32)
        self._pos = np.zeros(self.max_batch + 1, np.int32)
        self._slot_req: List[Optional[_Request]] = [None] * self.max_batch
        # Admission queue: a HEAP keyed (deadline slack, seq) — the 10k
        # flagship load would pay an O(n log n) re-sort per scheduler
        # iteration under the engine lock with a sorted list. Expiry and
        # cancellation are lazy (checked at pop); _n_queued is the live
        # count (heap entries may be dead).
        self._queue: List[tuple] = []
        self._n_queued = 0
        self._streams: Dict[str, _Stream] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        self._seq = 0
        self._step_errors_row = 0
        # Last wall-clock instant a token batch reached the streams —
        # the previous edge of the inter-token-latency (TPOT) gap.
        # None until the first prefill delivers (the first decode step
        # after a gap measures from the last delivery, so ITL includes
        # scheduling stalls between steps, not just compute).
        self._last_tokens_at: Optional[float] = None
        self._last_reap = time.monotonic()
        self.stats_counters = {
            "steps": 0, "admitted": 0, "completed": 0, "shed": 0,
            "errors": 0, "tokens_out": 0, "queue_peak": 0,
            "occupancy_sum": 0, "ring_wraps": 0,
        }
        threading.Thread(target=self._loop, daemon=True,
                         name="llm-engine-loop").start()

    # -- scheduler loop ----------------------------------------------------

    def _loop(self):  # jax-hot-path
        while not self._stop:
            did = False
            try:
                did = self._admit_once() or did
            except BaseException:
                # _admit_once handles its own requeue; anything that
                # still escapes must not kill the scheduler — but a
                # scheduler stuck in a crash-restart cycle must be
                # visible on the scrape, not just a silent hot core.
                _metrics.count_loop_restart("llm.engine")
            try:
                did = self._step_once() or did
            except BaseException:
                # Step errors are already counted (3-strike fail-fast
                # in _step_once); this tick records the loop survival.
                _metrics.count_loop_restart("llm.engine")
            if time.monotonic() - self._last_reap > 5.0:
                self._reap_streams()
            if not did:
                self._wake.wait(0.02)
                self._wake.clear()

    def _push_queued_locked(self, req: _Request):
        """Heap key = (deadline, seq): admission prefers deadline slack
        — tightest budget first, FIFO among the unbounded. seq is
        unique, so _Request itself is never compared."""
        dl = req.deadline_ts if req.deadline_ts is not None \
            else float("inf")
        heapq.heappush(self._queue, (dl, req.seq, req))
        self._n_queued += 1

    def _shed_expired_locked(self, now: float):
        """Typed-shed the expired HEAD of the queue (caller holds the
        lock). The heap is deadline-ordered, so expired entries are a
        prefix — this is O(expired), not O(queue), and it runs every
        iteration so a dead budget sheds at the next step boundary even
        when no slot ever frees (a saturated engine must not hold a
        dead request's poller hostage)."""
        while self._queue:
            dl, _, req = self._queue[0]
            if req.stream.done:
                heapq.heappop(self._queue)  # cancelled: drop lazily
                continue
            if dl == float("inf") or now <= dl:
                break
            heapq.heappop(self._queue)
            self._n_queued -= 1
            self._finish_locked(req, shed="decode")

    def _admit_once(self) -> bool:
        with self._lock:
            now = time.time()
            self._shed_expired_locked(now)
            free = [i for i in range(self.max_batch)
                    if self._slot_req[i] is None]
            if not free or not self._n_queued:
                return False
            take = min(len(free), self.prefill_rows)
            batch: List[_Request] = []
            while self._queue and len(batch) < take:
                _, _, req = heapq.heappop(self._queue)
                if req.stream.done:
                    continue  # cancelled in queue: already accounted
                self._n_queued -= 1
                if req.deadline_ts is not None \
                        and now > req.deadline_ts:
                    # The budget died waiting for a slot: typed shed,
                    # reason=decode (the engine owns the budget once
                    # the router handed the request over).
                    self._finish_locked(req, shed="decode")
                    continue
                batch.append(req)
            if not batch:
                # Expired/cancelled entries were drained — progress.
                return True
            slots = free[:len(batch)]  # slot-guard: _push_queued_locked,_finish_locked
            for req in batch:
                # Admission: queue phase ends, prefill phase starts
                # (the span covers the prefill compute below).
                self._phase_span_locked(req, "llm.prefill")
        try:
            failpoints.hit("serve.llm.before_admit")
            self._prefill_batch(batch, slots)
        except BaseException as e:  # noqa: BLE001 — requeue, bounded
            with self._lock:
                for req in batch:
                    req.retries += 1
                    if req.retries > 3:
                        self._finish_locked(req, error=repr(e))
                    else:
                        # Back to the queue: the failed prefill span
                        # closes errored and a fresh queue span opens —
                        # an open span must never re-enter the heap.
                        self._phase_span_locked(
                            req, "llm.queue",
                            status="ERROR: prefill_retry")
                        self._push_queued_locked(req)
        return True

    def _prefill_batch(self, batch: List[_Request], slots: List[int]):  # jax-hot-path
        np = self._np
        rows = self.prefill_rows
        p_len = self.max_prompt_len
        toks = np.zeros((rows, p_len), np.int32)
        slot_idx = np.full(rows, self.max_batch, np.int32)  # scratch row
        lengths = np.ones(rows, np.int32)
        for i, req in enumerate(batch):
            prompt = req.prompt[-p_len:]  # truncate to the lane window
            toks[i, :len(prompt)] = prompt
            slot_idx[i] = slots[i]
            lengths[i] = len(prompt)
        first, self._cache = self._prefill_fn(
            self.params, self._cache, self._jnp.asarray(toks),
            self._jnp.asarray(slot_idx), self._jnp.asarray(lengths))
        # The one intentional sync per prefill: first tokens must reach
        # the streams now.  # analyze: ignore[JX002]
        first = np.asarray(first)  # analyze: ignore[JX002]
        now = time.time()
        _obs.record_decode_tokens(self._dep, len(batch))
        with self._lock:
            for i, req in enumerate(batch):
                slot = slots[i]
                tok = int(first[i])
                self._tokens[slot] = tok
                self._pos[slot] = int(lengths[i])
                self._slot_req[slot] = req
                req.remaining = req.max_new - 1
                self.stats_counters["admitted"] += 1
                self.stats_counters["tokens_out"] += 1
                req.stream.n_tokens += 1
                req.stream.pending.append([tok])
                req.stream.event.set()
                # TTFT: submit -> first token available for delivery.
                _obs.record_ttft(self._dep, max(0.0, now - req.submitted))
                # First token exists: prefill phase ends HERE (the TTFT
                # decomposition keys on the prefill span's end), decode
                # phase runs until the terminal transition.
                self._phase_span_locked(req, "llm.decode")
                if req.remaining <= 0 or tok == self.eos_token:
                    self._finish_locked(req, done=True, slot=slot)
            self._last_tokens_at = now

    def step_cost(self) -> dict:
        """Cost-account the compiled decode step (util/xla_cost):
        FLOPs / bytes / roofline from the HLO, computed once and
        cached. Opt-in — the lowering pays one extra XLA compile, so
        the decode loop never does this on its own; once called, every
        subsequent step's anatomy event carries MFU."""
        if self._step_cost is None:
            from ray_tpu.util import xla_cost as _xla_cost

            cost = _xla_cost.step_cost(
                self._cost_fn, self.params, self._cache,
                self._jnp.asarray(self._tokens),
                self._jnp.asarray(self._pos))
            self._step_cost = cost
            if cost.get("available"):
                self._step_cost_flops = float(cost.get("flops", 0.0))
        return self._step_cost

    def _step_once(self) -> bool:  # jax-hot-path  # step-timed
        np = self._np
        with self._lock:
            now = time.time()
            # Deadline eviction happens at the step boundary: the slot
            # frees NOW, before compute, and the shed is typed.
            for slot in range(self.max_batch):
                req = self._slot_req[slot]
                if req is not None and req.deadline_ts is not None \
                        and now > req.deadline_ts:
                    self._finish_locked(req, shed="decode", slot=slot)
            active = [i for i in range(self.max_batch)
                      if self._slot_req[i] is not None]
            if not active:
                return False
            # Per-decode-step span: ONE per engine step (not one per
            # traced request per step — that would square the span
            # volume), parented under the oldest traced request's
            # decode span so it lands inside a real trace.
            step_parent = None
            for slot in active:
                req = self._slot_req[slot]
                if req is not None and req.span is not None and (
                        step_parent is None
                        or req.submitted < step_parent[0]):
                    step_parent = (req.submitted, req.span)
        step_span = tracing.start_span(
            "llm.step", {"occupancy": len(active)},
            parent={"trace_id": step_parent[1]["trace_id"],
                    "span_id": step_parent[1]["span_id"]},
            cat="llm") if step_parent is not None else None
        t0 = time.perf_counter()
        try:
            # The failpoint lives INSIDE the error-counted region: a
            # raise-armed before_step must trip the 3-strike fail-fast
            # (streams error out), not silently skip every step while
            # the site stays armed — that would be the hang the
            # never-hang contract forbids.
            failpoints.hit("serve.llm.before_step")
            nxt, self._cache = self._step_fn(
                self.params, self._cache, self._jnp.asarray(self._tokens),
                self._jnp.asarray(self._pos))
            # Anatomy host phase ends when the async dispatch returns.
            t_dispatch = time.perf_counter()
            # The one intentional sync per decode step (tokens fan out
            # to streams from host memory).  # analyze: ignore[JX002]
            nxt = np.asarray(nxt)  # analyze: ignore[JX002]
        except BaseException:
            tracing.finish_span(step_span, "ERROR: step")
            self._step_errors_row += 1
            self.stats_counters["errors"] += 1
            if self._step_errors_row >= _MAX_STEP_ERRORS:
                with self._lock:
                    for slot in range(self.max_batch):
                        req = self._slot_req[slot]
                        if req is not None:
                            self._finish_locked(
                                req, error="decode step failing "
                                "repeatedly", slot=slot)
                self._step_errors_row = 0
            raise
        self._step_errors_row = 0
        step_s = time.perf_counter() - t0
        with self._lock:
            produced = 0
            for slot in active:
                req = self._slot_req[slot]
                if req is None:
                    continue  # cancelled while the step was in flight
                tok = int(nxt[slot])
                self._tokens[slot] = tok
                self._pos[slot] += 1
                if int(self._pos[slot]) % self.cache_len == 0:
                    self.stats_counters["ring_wraps"] += 1
                req.remaining -= 1
                produced += 1
                req.stream.n_tokens += 1
                req.stream.pending.append([tok])
                req.stream.event.set()
                if req.remaining <= 0 or tok == self.eos_token:
                    self._finish_locked(req, done=True, slot=slot)
            self.stats_counters["steps"] += 1
            self.stats_counters["tokens_out"] += produced
            self.stats_counters["occupancy_sum"] += len(active)
            # ITL (TPOT): delivery-to-delivery gap. All slots advance
            # in lockstep, so every token this step produced arrived
            # the same gap after its stream's previous one — one event
            # carries the shared gap plus the token count.
            done_at = time.time()
            itl = step_s if self._last_tokens_at is None \
                else max(0.0, done_at - self._last_tokens_at)
            self._last_tokens_at = done_at
        _obs.record_decode_step(self._dep, step_s, len(active), produced)
        _obs.record_decode_itl(self._dep, itl, produced)
        # Step anatomy: host = dispatch wall, compute = the sync wall
        # after it (the np.asarray above IS the device wait); a
        # single-replica engine has no gang barrier, so sync is 0 and
        # host + compute partition step_s exactly. MFU rides along
        # once step_cost() has attached the HLO cost model.
        host_s = max(0.0, t_dispatch - t0)
        mfu = None
        if self._step_cost_flops > 0 and step_s > host_s:
            from ray_tpu.util import xla_cost as _xla_cost

            mfu = _xla_cost.mfu_percent(
                self._step_cost_flops, step_s - host_s)
        try:
            _goodput.record_anatomy(
                f"serve:{self._dep}", 0,
                {"data_wait": 0.0, "host": host_s,
                 "compute": max(0.0, step_s - host_s), "sync": 0.0},
                mfu=mfu)
        except Exception:
            pass
        if step_span is not None:
            step_span["attributes"]["tokens"] = produced
            tracing.finish_span(step_span)
        if self.step_throttle_s:
            time.sleep(self.step_throttle_s)
        return True

    def _phase_span_locked(self, req: _Request, name: Optional[str],
                           status: str = "OK") -> None:
        """Close the request's open phase span and (when ``name``) open
        the next one — at most one open span per request, every
        transition closes before it opens (caller holds the lock).
        No-op end to end for untraced requests."""
        if req.span is not None:
            tracing.finish_span(req.span, status)
            req.span = None
        if name is not None and req.trace_ctx and tracing.is_enabled():
            req.span = tracing.start_span(
                name, {"rid": req.rid, "deployment": self._dep},
                parent=req.trace_ctx, cat="llm")

    def _finish_locked(self, req: _Request, done: bool = False,
                       shed: Optional[str] = None,
                       error: Optional[str] = None,
                       slot: Optional[int] = None):
        """Terminal transition (caller holds the lock): free the slot,
        mark the stream, wake pollers, count the outcome."""
        if slot is not None and self._slot_req[slot] is req:
            self._slot_req[slot] = None
        st = req.stream
        if st.done:
            return
        st.done = True
        st.shed = shed
        st.error = error
        st.event.set()
        if shed is not None:
            self.stats_counters["shed"] += 1
            _obs.record_shed(self._dep, shed)
        elif error is not None:
            self.stats_counters["errors"] += 1
        else:
            self.stats_counters["completed"] += 1
        # Every terminal path funnels here, so this is THE place the
        # request's open phase span closes — queued (shed/cancel),
        # decoding (done/shed/error), step-failure fan-out alike.
        self._phase_span_locked(
            req, None,
            status="OK" if done and not shed and not error
            else f"ERROR: {shed or error or 'aborted'}")

    def _reap_streams(self):
        self._last_reap = time.monotonic()
        cutoff = time.monotonic() - _STREAM_TTL_S
        with self._lock:
            # Fully-delivered streams leave the table at delivery
            # (_drain_locked); only DONE streams nobody polls linger.
            for rid in [r for r, s in self._streams.items()
                        if s.done and s.last_poll < cutoff]:
                del self._streams[rid]

    # -- request surface (called through Replica.handle_request) ----------

    def _normalize(self, prompt, max_new_tokens):
        if isinstance(prompt, dict):
            max_new_tokens = prompt.get("max_tokens", max_new_tokens)
            prompt = prompt.get("tokens")
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(prompt, ObjectRef):
            # The shm handoff lane: the proxy put the prompt payload in
            # the object store and handed us the ref — the fetch is a
            # same-node shared-memory read, not a copy over the wire.
            import ray_tpu

            prompt = ray_tpu.get(prompt, timeout=30.0)
        if not prompt or not all(isinstance(t, int) for t in prompt):
            raise ValueError("prompt must be a non-empty list of token "
                             "ids (or {'tokens': [...]})")
        if max_new_tokens is None:
            max_new_tokens = self.max_new_tokens
        return list(prompt), max(1, min(int(max_new_tokens),
                                        self.max_new_cap))

    def llm_submit(self, prompt, max_new_tokens=None,
                   deadline_ts: Optional[float] = None) -> str:
        """Admit a request into the engine queue; returns the stream id.
        A full queue sheds typed (reason=decode) instead of erroring —
        admission under a full BATCH merely queues."""
        prompt, max_new = self._normalize(prompt, max_new_tokens)
        # The caller's span context rides the serve request scope (set
        # by Replica.handle_request); read on THIS thread, before the
        # request crosses to the engine loop's.
        trace_ctx = (_obs.current_request() or {}).get("trace_ctx")
        if trace_ctx:
            tracing.enable()  # the caller traces: continue here
        with self._lock:
            if self._n_queued >= self.max_queue:
                _obs.record_shed(self._dep, "decode")
                self.stats_counters["shed"] += 1
                raise RequestShedError(
                    f"llm engine queue full ({self.max_queue})",
                    reason="decode")
            self._seq += 1
            rid = f"llm-{os.getpid():x}-{self._seq:x}"
            req = _Request(rid, prompt, max_new, deadline_ts, self._seq,
                           trace_ctx=trace_ctx)
            self._push_queued_locked(req)
            self._phase_span_locked(req, "llm.queue")
            self.stats_counters["queue_peak"] = max(
                self.stats_counters["queue_peak"], self._n_queued)
            self._streams[rid] = req.stream
        self._wake.set()
        return rid

    def llm_submit_many(self, requests: List[dict]) -> List[str]:
        """Batched submit (the 10k-stream bench lane): each entry is
        {"tokens": [...], "max_tokens": n, "deadline_ts": ts|None}."""
        return [self.llm_submit(r.get("tokens"), r.get("max_tokens"),
                                r.get("deadline_ts")) for r in requests]

    def _drain_locked(self, rid: str, st: _Stream) -> dict:
        chunks, st.pending = st.pending, []
        st.last_poll = time.monotonic()
        resp = {"chunks": chunks, "done": st.done, "shed": st.shed,
                "error": st.error}
        if st.done and not st.pending:
            st.delivered = True
            self._streams.pop(rid, None)
        return resp

    def llm_next(self, rid: str, timeout_s: float = 2.0) -> dict:
        """Long-poll one stream: blocks until >=1 chunk (or the terminal
        transition) is available, up to ``timeout_s``."""
        with self._lock:
            st = self._streams.get(rid)
        if st is None:
            return {"chunks": [], "done": True, "shed": None,
                    "error": f"unknown stream {rid!r}"}
        st.event.wait(max(0.0, float(timeout_s)))
        with self._lock:
            resp = self._drain_locked(rid, st)
            if not st.done:
                st.event.clear()
        return resp

    def llm_poll(self, rids: List[str]) -> Dict[str, dict]:
        """Non-blocking batched drain (the bench's collector lane)."""
        out = {}
        with self._lock:
            for rid in rids:
                st = self._streams.get(rid)
                if st is None:
                    out[rid] = {"chunks": [], "done": True, "shed": None,
                                "error": f"unknown stream {rid!r}"}
                else:
                    out[rid] = self._drain_locked(rid, st)
        return out

    def llm_cancel(self, rid: str) -> bool:
        """Cancel a stream: a queued request leaves the queue, an
        active one frees its slot at the cancel (the in-flight step's
        token for it is discarded). The stream terminates with a
        'cancelled' error; returns whether the request was still live.
        A request mid-admission (its prefill in flight) is in neither
        table and returns False — it completes normally and is reaped;
        the window is one prefill call."""
        with self._lock:
            for slot in range(self.max_batch):
                req = self._slot_req[slot]
                if req is not None and req.rid == rid:
                    self._finish_locked(req, error="cancelled",
                                        slot=slot)
                    return True
            for _, _, req in self._queue:
                if req.rid == rid and not req.stream.done:
                    # The heap entry stays and is dropped lazily at
                    # pop; the live count updates now.
                    self._n_queued -= 1
                    self._finish_locked(req, error="cancelled")
                    return True
        return False

    def generate(self, prompt, max_new_tokens=None,
                 deadline_ts: Optional[float] = None,
                 timeout_s: Optional[float] = None) -> List[int]:
        """Blocking request/response lane: submit, drain own stream,
        return the generated tokens. Sheds raise typed. On timeout the
        orphaned request is CANCELLED (slot freed, queue entry
        dropped) — an abandoned caller must not leave the engine
        decoding tokens nobody reads."""
        rid = self.llm_submit(prompt, max_new_tokens, deadline_ts)
        if timeout_s is None:
            # A caller-supplied deadline bounds the wait (+grace for
            # the final drain); without one, a generous static cap.
            timeout_s = 300.0 if deadline_ts is None else max(
                5.0, deadline_ts - time.time() + 30.0)
        out: List[int] = []
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            resp = self.llm_next(rid, timeout_s=2.0)
            for chunk in resp["chunks"]:
                out.extend(chunk)
            if resp["done"]:
                if resp["shed"]:
                    raise RequestShedError(
                        f"llm request shed: {resp['shed']}",
                        reason=resp["shed"])
                if resp["error"]:
                    raise RuntimeError(resp["error"])
                return out
        self.llm_cancel(rid)
        raise TimeoutError(
            f"llm generate did not finish within {timeout_s:.0f}s "
            f"(request cancelled)")

    def __call__(self, payload) -> dict:
        """HTTP/graph lane: {"tokens": [...], "max_tokens": n} ->
        {"tokens": [generated...]}. The serve request context's
        deadline (handle.options(deadline_s=...) / the deadline header)
        carries into the engine, so the blocking lane gets the same
        mid-decode shed semantics as the streaming lane."""
        ctx = _obs.current_request() or {}
        return {"tokens": self.generate(
            payload, deadline_ts=ctx.get("deadline_ts"))}

    def llm_stats(self) -> dict:
        with self._lock:
            active = sum(1 for r in self._slot_req if r is not None)
            queued = self._n_queued
            c = dict(self.stats_counters)
        steps = c["steps"]
        return {
            "model": self.model,
            "max_batch": self.max_batch,
            "cache_len": self.cache_len,
            "max_prompt_len": self.max_prompt_len,
            "prefill_rows": self.prefill_rows,
            "active": active,
            "queued": queued,
            "compiles": dict(self._compiles),
            "mean_occupancy": round(c["occupancy_sum"] / steps, 3)
            if steps else 0.0,
            **c,
        }

    def set_deployment_name(self, name: str) -> None:
        """Called by the Replica wrapper at construction so the decode
        metric families carry the ACTUAL deployment name — without it,
        an engine deployed under any name but the bind-arg default
        would be invisible to the stats join."""
        if not self._dep_explicit and name:
            self._dep = name

    def check_health(self) -> str:
        return "ok"

    def shutdown_engine(self) -> bool:
        self._stop = True
        self._wake.set()
        _metrics.retract_loop_series(["llm.engine"])
        # The engine's per-step anatomy gauges (MFU / phase seconds)
        # must not outlive it on the scrape (LC001 discipline).
        try:
            _goodput.retract_trial(f"serve:{self._dep}")
        except Exception:
            pass
        return True
