"""Serve: scalable model serving on actors.

Reference parity: ``python/ray/serve`` (SURVEY.md §2.3, §3.5) —
``@serve.deployment`` -> ``serve.run`` -> controller-reconciled replica
actors, handles with power-of-two routing + backpressure, an HTTP proxy,
and ``@serve.batch`` dynamic batching. On TPU the replica's callable
typically wraps a jitted inference function; replicas-per-chip is the
scaling unit.
"""

from __future__ import annotations

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("serve")

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.serve import _observability, _private
from ray_tpu.serve._observability import RequestShedError
from ray_tpu.serve._private import (
    CONTROLLER_NAME,
    DEADLINE_HEADER,
    STREAM_HEADER,
    DeploymentHandle,
    HTTPProxy,
    batch,
    get_or_create_controller,
)


def __getattr__(name: str):
    # The LLM engine pulls in jax; resolve it lazily so importing serve
    # on a jax-less control-plane process stays cheap.
    if name == "LLMEngine":
        from ray_tpu.serve.llm_engine import LLMEngine

        return LLMEngine
    raise AttributeError(name)


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    route_prefix: Optional[str] = None
    version: Optional[str] = None
    user_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    # Queue-depth autoscaling (reference autoscaling_policy.py): keys
    # min_replicas, max_replicas, target_ongoing_requests,
    # downscale_delay_s. None = fixed num_replicas.
    autoscaling_config: Optional[dict] = None
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)

    def options(self, **kwargs) -> "Deployment":
        known = {f for f in self.__dataclass_fields__}  # noqa: C416
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"unknown deployment options: {bad}")
        merged = {**self.__dict__, **kwargs}
        return Deployment(**merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               route_prefix: Optional[str] = None,
               version: Optional[str] = None,
               user_config: Optional[dict] = None,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None):
    """``@serve.deployment`` decorator (``python/ray/serve/api.py``)."""

    def wrap(target):
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            route_prefix=route_prefix,
            version=version,
            user_config=user_config,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscaling_config,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def _resolve_graph_arg(value, controller, used_names: dict):
    """Deployment-graph composition (reference
    ``serve/deployment_graph_build.py``): a bound deployment appearing in
    another deployment's init args deploys first (post-order DFS) and is
    replaced by a ``DeploymentHandle`` — the constructed replica holds
    live handles to its upstream models."""
    if isinstance(value, Deployment):
        value = value.bind()
    if isinstance(value, Application):
        inner = _deploy_app(value, controller, route_prefix=None,
                            used_names=used_names)
        return DeploymentHandle(inner)
    if isinstance(value, (list, tuple)):
        resolved = [_resolve_graph_arg(v, controller, used_names)
                    for v in value]
        return type(value)(resolved)
    if isinstance(value, dict):
        return {k: _resolve_graph_arg(v, controller, used_names)
                for k, v in value.items()}
    return value


def _deploy_app(target: "Application", controller,
                name: Optional[str] = None,
                route_prefix: Optional[str] = "__use_deployment__",
                used_names: Optional[dict] = None) -> str:
    used_names = used_names if used_names is not None else {}
    dep = target.deployment
    init_args = tuple(
        _resolve_graph_arg(a, controller, used_names)
        for a in target.init_args)
    init_kwargs = {
        k: _resolve_graph_arg(v, controller, used_names)
        for k, v in target.init_kwargs.items()
    }
    prefix = dep.route_prefix if route_prefix == "__use_deployment__" \
        else route_prefix
    # Unique graph-node names (reference graph build does the same): two
    # bindings of one deployment in a graph are distinct deployments —
    # without this the second would silently redeploy over the first.
    final = name or dep.name
    count = used_names.get(final, 0)
    used_names[final] = count + 1
    if count:
        final = f"{final}_{count + 1}"
    ray_tpu.get(
        controller.deploy.remote(
            final,
            dep.func_or_class,
            init_args,
            init_kwargs,
            dep.num_replicas,
            dep.max_concurrent_queries,
            prefix,
            dep.version,
            dep.ray_actor_options,
            dep.autoscaling_config,
        ),
        timeout=120,
    )
    return final


def run(target: "Application | Deployment", *, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy (or redeploy) an application — possibly a deployment GRAPH
    whose init args contain other bound deployments — and return a handle
    (``serve/api.py:455`` + graph build)."""
    if isinstance(target, Deployment):
        target = target.bind()
    controller = get_or_create_controller()
    prefix = route_prefix if route_prefix is not None else "__use_deployment__"
    deployed = _deploy_app(target, controller, name=name, route_prefix=prefix)
    return DeploymentHandle(deployed)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def status() -> Dict[str, dict]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


def stats(window_s: float = 0.0,
          allow_sleep: bool = True) -> Dict[str, dict]:
    """Per-deployment serving stats from the SLO latency plane:
    replica counts, p50/p99/mean request latency, per-phase breakdown
    (route / queue_wait / batch_wait / execute / serialize), status and
    shed counts, live ongoing/queued gauges. ``window_s > 0`` adds a
    measured QPS over that window — answered from the head's metrics
    history ring when one is reachable; ``allow_sleep=False`` forbids
    the off-cluster double-scrape fallback (request paths like the
    dashboard must never stall). Surfaced as ``ray-tpu serve stats``
    and the dashboard's ``/api/serve_stats``."""
    return _observability.stats(window_s, allow_sleep=allow_sleep)


_proxy_handle = None


@deployment(name="DAGDriver", route_prefix="/")
class DAGDriver:
    """HTTP ingress for a deployment graph (reference
    ``serve/drivers.py`` DAGDriver): bind it over a composed application
    — ``serve.run(DAGDriver.bind(graph))`` — and each request payload is
    fed to the graph's root handle. ``http_adapter`` optionally reshapes
    the decoded JSON body first."""

    def __init__(self, graph: DeploymentHandle, http_adapter=None):
        self._handle = graph
        self._adapter = http_adapter

    def __call__(self, request):
        payload = self._adapter(request) if self._adapter else request
        return ray_tpu.get(self._handle.remote(payload), timeout=120)


def start_http_proxy(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start a single HTTP ingress; returns the bound port. For one
    ingress per node (reference default: an HTTPProxyActor on every node,
    ``http_state.py:30``) use :func:`start_http_proxies`."""
    global _proxy_handle
    proxy_cls = ray_tpu.remote(HTTPProxy)
    _proxy_handle = proxy_cls.options(num_cpus=0, max_concurrency=16).remote(
        host, port
    )
    return ray_tpu.get(_proxy_handle.get_port.remote(), timeout=60)


def start_http_proxies(host: str = "127.0.0.1") -> Dict[str, int]:
    """One HTTP ingress per alive node, owned and kept alive by the
    controller: a dead proxy (or a proxy whose node died) is recreated on
    the next reconcile tick, and new nodes get proxies as they join.
    Returns {node_id: port}; call :func:`proxy_ports` later for the
    current mapping (recreated proxies bind fresh ports)."""
    controller = _private.get_or_create_controller()
    return ray_tpu.get(
        controller.ensure_proxies.remote(host), timeout=120)


def proxy_ports() -> Dict[str, int]:
    """Current {node_id: port} of the controller-managed proxy fleet."""
    controller = _private.get_or_create_controller()
    return ray_tpu.get(controller.proxy_ports.remote(), timeout=30)


def shutdown() -> None:
    global _proxy_handle
    from ray_tpu.serve import _private as _serve_private

    _serve_private.reset_routers()
    if _proxy_handle is not None:
        try:
            ray_tpu.get(_proxy_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_proxy_handle)
        except Exception:
            pass
        _proxy_handle = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown_all.remote(), timeout=60)
        ray_tpu.kill(controller)
    except ValueError:
        pass


__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "DeploymentHandle",
    "DAGDriver",
    "run",
    "get_deployment_handle",
    "get_app_handle",
    "delete",
    "status",
    "stats",
    "RequestShedError",
    "DEADLINE_HEADER",
    "STREAM_HEADER",
    "LLMEngine",
    "start_http_proxy",
    "start_http_proxies",
    "proxy_ports",
    "shutdown",
    "batch",
]
