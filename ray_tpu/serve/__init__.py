"""Serve: scalable model serving on actors.

Reference parity: ``python/ray/serve`` (SURVEY.md §2.3, §3.5) —
``@serve.deployment`` -> ``serve.run`` -> controller-reconciled replica
actors, handles with power-of-two routing + backpressure, an HTTP proxy,
and ``@serve.batch`` dynamic batching. On TPU the replica's callable
typically wraps a jitted inference function; replicas-per-chip is the
scaling unit.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

import ray_tpu
from ray_tpu.serve._private import (
    CONTROLLER_NAME,
    DeploymentHandle,
    HTTPProxy,
    batch,
    get_or_create_controller,
)


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    route_prefix: Optional[str] = None
    version: Optional[str] = None
    user_config: Optional[dict] = None
    ray_actor_options: Optional[dict] = None
    # Queue-depth autoscaling (reference autoscaling_policy.py): keys
    # min_replicas, max_replicas, target_ongoing_requests,
    # downscale_delay_s. None = fixed num_replicas.
    autoscaling_config: Optional[dict] = None
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)

    def options(self, **kwargs) -> "Deployment":
        known = {f for f in self.__dataclass_fields__}  # noqa: C416
        bad = set(kwargs) - known
        if bad:
            raise ValueError(f"unknown deployment options: {bad}")
        merged = {**self.__dict__, **kwargs}
        return Deployment(**merged)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclass
class Application:
    deployment: Deployment
    init_args: tuple
    init_kwargs: dict


def deployment(_func_or_class=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 100,
               route_prefix: Optional[str] = None,
               version: Optional[str] = None,
               user_config: Optional[dict] = None,
               ray_actor_options: Optional[dict] = None,
               autoscaling_config: Optional[dict] = None):
    """``@serve.deployment`` decorator (``python/ray/serve/api.py``)."""

    def wrap(target):
        return Deployment(
            func_or_class=target,
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            route_prefix=route_prefix,
            version=version,
            user_config=user_config,
            ray_actor_options=ray_actor_options,
            autoscaling_config=autoscaling_config,
        )

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap


def run(target: "Application | Deployment", *, name: Optional[str] = None,
        route_prefix: Optional[str] = None) -> DeploymentHandle:
    """Deploy (or redeploy) and return a handle
    (``serve/api.py:455``)."""
    if isinstance(target, Deployment):
        target = target.bind()
    dep = target.deployment
    controller = get_or_create_controller()
    ray_tpu.get(
        controller.deploy.remote(
            name or dep.name,
            dep.func_or_class,
            target.init_args,
            target.init_kwargs,
            dep.num_replicas,
            dep.max_concurrent_queries,
            route_prefix if route_prefix is not None else dep.route_prefix,
            dep.version,
            dep.ray_actor_options,
            dep.autoscaling_config,
        ),
        timeout=120,
    )
    return DeploymentHandle(name or dep.name)


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def get_app_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def delete(name: str) -> None:
    controller = get_or_create_controller()
    ray_tpu.get(controller.delete_deployment.remote(name), timeout=60)


def status() -> Dict[str, dict]:
    controller = get_or_create_controller()
    return ray_tpu.get(controller.status.remote(), timeout=30)


_proxy_handle = None


def start_http_proxy(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP ingress; returns the bound port."""
    global _proxy_handle
    proxy_cls = ray_tpu.remote(HTTPProxy)
    _proxy_handle = proxy_cls.options(num_cpus=0, max_concurrency=16).remote(
        host, port
    )
    return ray_tpu.get(_proxy_handle.get_port.remote(), timeout=60)


def shutdown() -> None:
    global _proxy_handle
    from ray_tpu.serve import _private as _serve_private

    _serve_private.reset_routers()
    if _proxy_handle is not None:
        try:
            ray_tpu.get(_proxy_handle.stop.remote(), timeout=10)
            ray_tpu.kill(_proxy_handle)
        except Exception:
            pass
        _proxy_handle = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        ray_tpu.get(controller.shutdown_all.remote(), timeout=60)
        ray_tpu.kill(controller)
    except ValueError:
        pass


__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "DeploymentHandle",
    "run",
    "get_deployment_handle",
    "get_app_handle",
    "delete",
    "status",
    "start_http_proxy",
    "shutdown",
    "batch",
]
