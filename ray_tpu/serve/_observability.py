"""Serve request-path observability: the SLO latency plane.

Every hop of a serve request — router assign, replica ongoing queue,
``@serve.batch`` queue, user callable, response serialize — records a
phase observation here. Recording is two-sided by design:

* the observation lands in THIS process's metric registry immediately
  (the local backend runs replicas as in-process threads, so the
  process registry is exactly what ``/metrics`` scrapes there);
* the same observation is appended to a bounded ship buffer that the
  worker's event flusher drains over the existing worker-events plane
  (``rpc_worker_events`` grew a ``serve`` batch), so on the cluster
  backend — where routers, replicas and proxies are worker processes
  whose registries nothing scrapes — the node agent replays it into
  the agent registry that federates on ``/metrics/cluster``.

Gauge children created by a worker's events are tracked per worker by
the agent and retracted when the worker dies (PR 3/4 retraction
discipline: a dead replica must vanish from the federated scrape).

Also here: the per-request deadline context that rides the trace
context (``RequestShedError`` is what the router / replica / batch
queue raise instead of executing dead work), and the Prometheus-text
parsing used by ``serve.stats()`` and ``scripts/serve_bench.py`` to
read the histograms back — the same parser serves the CLI, the
dashboard and the client/server cross-check, so they can never
disagree about what the exposition says.
"""

from __future__ import annotations

import collections
import contextvars
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _metrics

# Phases a request can observe (the serve histogram's phase tag values).
PHASES = ("route", "queue_wait", "batch_wait", "execute", "serialize",
          "total")


class RequestShedError(Exception):
    """A request whose deadline expired before execution: shed by the
    router, the replica, or the batch queue instead of running dead
    work. The HTTP proxy maps it to 503."""

    def __init__(self, message: str, reason: str = "deadline"):
        super().__init__(message)
        self.reason = reason


# -- per-request context (deadline rides the trace context) ----------------

_request_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_serve_request", default=None)


@contextmanager
def request_scope(deployment: str, deadline_ts: Optional[float],
                  trace_ctx: Optional[dict] = None):
    """Active while the replica runs the user callable, so nested
    machinery (the @serve.batch queue, the LLM engine's admission path)
    can read the deployment name, the absolute deadline, and the
    caller's span context without threading arguments through user
    code. ``trace_ctx`` is what the engine parents its queue/prefill/
    decode spans under — the engine loop runs on its OWN thread, so the
    thread-local current-span stack cannot carry it there."""
    token = _request_ctx.set({"deployment": deployment,
                              "deadline_ts": deadline_ts,
                              "trace_ctx": trace_ctx})
    try:
        yield
    finally:
        _request_ctx.reset(token)


def current_request() -> Optional[dict]:
    return _request_ctx.get()


# -- recording -------------------------------------------------------------

_LOCAL_NODE = "local"
# Ship buffer drained by workerproc's event flusher; bounded so a
# process nothing drains (the local-backend driver) stays flat.
_buf: "collections.deque" = collections.deque(maxlen=8192)
_buf_lock = threading.Lock()
# Events the bounded buffer pushed out before a drain (nothing drains
# the local backend's driver, or the flusher fell behind a burst):
# reported as a drop event on the next drain — never a silent cap.
_buf_dropped = 0
# Router-side queue depth per deployment in THIS process.
_router_queued: Dict[str, int] = {}
_router_lock = threading.Lock()


def _emit(ev: dict) -> None:
    """Observe locally and queue for the agent (see module docstring)."""
    global _buf_dropped
    try:
        apply_events([ev], node_id=_LOCAL_NODE)
    except Exception:
        pass
    with _buf_lock:
        if len(_buf) == _buf.maxlen:
            _buf_dropped += 1  # deque discards the oldest silently
        _buf.append(ev)


def drain_events() -> List[dict]:
    """Pop queued observations (the worker event flusher's hook). A
    preceding overflow is reported as a leading drop event so the
    agent's registry counts exactly what this process lost."""
    global _buf_dropped
    with _buf_lock:
        out = list(_buf)
        _buf.clear()
        if _buf_dropped:
            out.insert(0, {"k": "drop", "n": _buf_dropped})
            _buf_dropped = 0
    return out


def requeue_events(events: List[dict]) -> None:
    """Put drained observations back at the FRONT of the ship buffer
    (the worker flusher calls this when the agent upload fails — a
    chaos-severed worker->agent channel must not silently lose request
    counts). Overflow beyond capacity is counted as drops, oldest
    first, like every other loss on this plane."""
    global _buf_dropped
    if not events:
        return
    with _buf_lock:
        space = _buf.maxlen - len(_buf)
        if space < len(events):
            _buf_dropped += len(events) - space
            events = events[len(events) - space:]
        _buf.extendleft(reversed(events))


def record_phases(deployment: str, phases: Dict[str, float]) -> None:
    """Observe wall seconds per request phase."""
    phases = {p: s for p, s in phases.items() if p in PHASES and s >= 0}
    if phases:
        _emit({"k": "ph", "d": deployment, "p": phases})


def record_status(deployment: str, status: str) -> None:
    """Count one terminal request outcome (router-side only — the one
    place every request passes exactly once)."""
    _emit({"k": "st", "d": deployment, "s": status})


def record_shed(deployment: str, reason: str) -> None:
    """Count one deadline shed at the site that shed it."""
    _emit({"k": "shed", "d": deployment, "r": reason})


def record_batch(deployment: str, size: int) -> None:
    _emit({"k": "batch", "d": deployment, "n": int(size)})


def record_reconcile(seconds: float) -> None:
    _emit({"k": "rec", "s": float(seconds)})


def record_decode_step(deployment: str, seconds: float, occupancy: int,
                       tokens: int) -> None:
    """One LLM-engine decode iteration: step wall time, active slots,
    tokens produced — a single event so a step is never half-recorded."""
    _emit({"k": "dstep", "d": deployment, "s": float(seconds),
           "o": int(occupancy), "n": int(tokens)})


def record_ttft(deployment: str, seconds: float) -> None:
    """Time to first token for one admitted stream."""
    _emit({"k": "ttft", "d": deployment, "s": float(seconds)})


def record_decode_itl(deployment: str, seconds: float,
                      tokens: int) -> None:
    """Inter-token latency (TPOT) for one decode step: every token the
    step produced arrived ``seconds`` after its stream's previous one
    (slots advance in lockstep), so one event carries the shared gap
    and the token count — replayed as ``tokens`` histogram
    observations."""
    if tokens > 0 and seconds >= 0:
        _emit({"k": "itl", "d": deployment, "s": float(seconds),
               "n": int(tokens)})


def record_decode_tokens(deployment: str, tokens: int) -> None:
    """Tokens produced outside a decode step (the prefill lane samples
    each admitted stream's FIRST token from the prefill logits)."""
    if tokens > 0:
        _emit({"k": "dtok", "d": deployment, "n": int(tokens)})


def set_replica_ongoing(deployment: str, replica: str, ongoing: int) -> None:
    _emit({"k": "g", "d": deployment, "r": replica, "n": int(ongoing)})


def router_queue_delta(deployment: str, delta: int) -> None:
    """Track requests blocked in this process's router ``assign`` and
    export the absolute depth (the queued-demand signal replicas can't
    see behind max_concurrent_queries)."""
    with _router_lock:
        n = max(0, _router_queued.get(deployment, 0) + delta)
        _router_queued[deployment] = n
    _emit({"k": "q", "d": deployment, "n": n})


def apply_events(events: List[dict], node_id: str,
                 worker: Optional[str] = None) -> List[Tuple]:
    """Replay shipped observations into THIS process's registry (the
    node agent calls this with its node_id + the reporting worker's id).
    Returns the gauge keys the batch touched so the agent can retract
    them when the worker dies."""
    worker = worker or str(os.getpid())
    gauge_keys: List[Tuple] = []
    for ev in events or []:
        try:
            kind = ev.get("k")
            dep = ev.get("d", "")
            if kind == "ph":
                for phase, sec in (ev.get("p") or {}).items():
                    _metrics.SERVE_REQUEST_SECONDS.observe(
                        float(sec), tags={"node_id": node_id,
                                          "deployment": dep,
                                          "phase": phase})
            elif kind == "st":
                _metrics.SERVE_REQUESTS_TOTAL.inc(
                    tags={"node_id": node_id, "deployment": dep,
                          "status": ev.get("s", "ok")})
            elif kind == "shed":
                _metrics.SERVE_SHED_TOTAL.inc(
                    tags={"node_id": node_id, "deployment": dep,
                          "reason": ev.get("r", "deadline")})
            elif kind == "batch":
                _metrics.SERVE_BATCH_SIZE.observe(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "deployment": dep})
            elif kind == "rec":
                _metrics.SERVE_RECONCILE_SECONDS.set(
                    float(ev.get("s", 0.0)), tags={"node_id": node_id})
                gauge_keys.append(("reconcile",))
            elif kind == "g":
                rep = ev.get("r", "")
                _metrics.SERVE_REPLICA_ONGOING.set(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "deployment": dep,
                          "replica": rep})
                gauge_keys.append(("ongoing", dep, rep))
            elif kind == "q":
                _metrics.SERVE_ROUTER_QUEUE_DEPTH.set(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "deployment": dep,
                          "worker": worker})
                gauge_keys.append(("queued", dep, worker))
            elif kind == "dstep":
                _metrics.SERVE_DECODE_STEP_SECONDS.observe(
                    float(ev.get("s", 0.0)),
                    tags={"node_id": node_id, "deployment": dep})
                _metrics.SERVE_DECODE_BATCH_OCCUPANCY.observe(
                    float(ev.get("o", 0)),
                    tags={"node_id": node_id, "deployment": dep})
                n_tok = float(ev.get("n", 0))
                if n_tok > 0:
                    _metrics.SERVE_DECODE_TOKENS_TOTAL.inc(
                        n_tok, tags={"node_id": node_id,
                                     "deployment": dep})
            elif kind == "ttft":
                _metrics.SERVE_DECODE_TTFT_SECONDS.observe(
                    float(ev.get("s", 0.0)),
                    tags={"node_id": node_id, "deployment": dep})
            elif kind == "itl":
                # One observation per token the step produced (the gap
                # is shared across the batch's streams); bounded far
                # above any real slot count so a corrupt event can't
                # spin the replay.
                gap = float(ev.get("s", 0.0))
                for _ in range(min(int(ev.get("n", 0)), 4096)):
                    _metrics.SERVE_DECODE_ITL_SECONDS.observe(
                        gap, tags={"node_id": node_id,
                                   "deployment": dep})
            elif kind == "dtok":
                _metrics.SERVE_DECODE_TOKENS_TOTAL.inc(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "deployment": dep})
            elif kind == "drop":
                _metrics.SERVE_EVENTS_DROPPED.inc(
                    float(ev.get("n", 0)), tags={"node_id": node_id})
        except Exception:
            continue  # one bad event must not drop the batch
    return gauge_keys


def retract_gauges(keys, node_id: str) -> None:
    """Drop the gauge children a dead worker's events created (the
    federated scrape must not keep reporting a dead replica)."""
    for key in keys or ():
        try:
            if key[0] == "ongoing":
                _metrics.SERVE_REPLICA_ONGOING.remove(tags={
                    "node_id": node_id, "deployment": key[1],
                    "replica": key[2]})
            elif key[0] == "queued":
                _metrics.SERVE_ROUTER_QUEUE_DEPTH.remove(tags={
                    "node_id": node_id, "deployment": key[1],
                    "worker": key[2]})
            elif key[0] == "reconcile":
                _metrics.SERVE_RECONCILE_SECONDS.remove(
                    tags={"node_id": node_id})
        except Exception:
            pass


# -- reading the plane back (serve.stats / serve_bench cross-check) --------
# The parser lives in util/metrics.py since the signal plane made it
# cluster infrastructure (the head's history ring ingests the same
# exposition this module reads back); re-exported here so every
# existing caller — goodput.py, the benches, the tests — keeps one
# import path and one definition.

from ray_tpu.util.metrics import (  # noqa: E402,F401
    _labels_get,
    bucket_width_at,
    diff_parsed,
    histogram_dist,
    parse_prometheus,
    quantile_from_buckets,
    sum_counter,
)


def metrics_text() -> str:
    """The scrape body of record: the head's federated
    ``/metrics/cluster`` on a cluster backend, this process's registry
    on the local backend."""
    from ray_tpu._private import worker as _worker

    try:
        backend = _worker.backend()
    except Exception:
        backend = None
    if backend is not None and hasattr(backend, "cluster_metrics_text"):
        try:
            return backend.cluster_metrics_text()
        except Exception:
            pass
    return _metrics.prometheus_text()


def deployment_stats(parsed: dict, deployment: str) -> dict:
    """One deployment's rollup from a parsed exposition snapshot."""
    out: dict = {"deployment": deployment}
    dist = histogram_dist(parsed, "ray_tpu_serve_request_seconds",
                          deployment=deployment, phase="total")
    if dist:
        out["count"] = int(dist["count"])
        out["mean_ms"] = round(dist["sum"] / dist["count"] * 1e3, 3)
        p50 = quantile_from_buckets(dist, 0.50)
        p99 = quantile_from_buckets(dist, 0.99)
        out["p50_ms"] = round(p50 * 1e3, 3) if p50 is not None else None
        out["p99_ms"] = round(p99 * 1e3, 3) if p99 is not None else None
    phases = {}
    for phase in PHASES:
        if phase == "total":
            continue
        d = histogram_dist(parsed, "ray_tpu_serve_request_seconds",
                           deployment=deployment, phase=phase)
        if d:
            p50 = quantile_from_buckets(d, 0.50)
            phases[phase] = {
                "count": int(d["count"]),
                "mean_ms": round(d["sum"] / d["count"] * 1e3, 3),
                "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
            }
    if phases:
        out["phases"] = phases
    statuses = sum_counter(parsed, "ray_tpu_serve_requests_total",
                           "status", deployment=deployment)
    if statuses:
        out["requests"] = {k: int(v) for k, v in statuses.items()}
    sheds = sum_counter(parsed, "ray_tpu_serve_shed_total", "reason",
                        deployment=deployment)
    if sheds:
        out["shed"] = {k: int(v) for k, v in sheds.items()}
    ongoing = sum_counter(parsed, "ray_tpu_serve_replica_ongoing",
                          "deployment", deployment=deployment)
    if ongoing:
        out["ongoing"] = int(sum(ongoing.values()))
    queued = sum_counter(parsed, "ray_tpu_serve_router_queue_depth",
                         "deployment", deployment=deployment)
    if queued:
        out["queued"] = int(sum(queued.values()))
    decode = decode_stats(parsed, deployment)
    if decode:
        out["decode"] = decode
    return out


def decode_stats(parsed: dict, deployment: str) -> dict:
    """LLM decode-engine rollup for one deployment (empty dict when the
    deployment runs no engine): TTFT quantiles, aggregate tokens,
    step/occupancy view — surfaced in ``serve.stats()``, the CLI and
    the dashboard alongside the request-phase plane."""
    out: dict = {}
    ttft = histogram_dist(parsed, "ray_tpu_serve_decode_ttft_seconds",
                          deployment=deployment)
    if ttft:
        out["streams"] = int(ttft["count"])
        p50 = quantile_from_buckets(ttft, 0.50)
        p99 = quantile_from_buckets(ttft, 0.99)
        out["ttft_p50_ms"] = round(p50 * 1e3, 3) if p50 is not None \
            else None
        out["ttft_p99_ms"] = round(p99 * 1e3, 3) if p99 is not None \
            else None
    itl = histogram_dist(parsed, "ray_tpu_serve_decode_itl_seconds",
                         deployment=deployment)
    if itl:
        p50 = quantile_from_buckets(itl, 0.50)
        out["itl_p50_ms"] = round(p50 * 1e3, 3) if p50 is not None \
            else None
    steps = histogram_dist(parsed, "ray_tpu_serve_decode_step_seconds",
                           deployment=deployment)
    if steps:
        out["steps"] = int(steps["count"])
        out["step_mean_ms"] = round(
            steps["sum"] / steps["count"] * 1e3, 3)
    occ = histogram_dist(parsed,
                         "ray_tpu_serve_decode_batch_occupancy",
                         deployment=deployment)
    if occ:
        out["mean_occupancy"] = round(occ["sum"] / occ["count"], 3)
    tokens = sum_counter(parsed, "ray_tpu_serve_decode_tokens_total",
                         "deployment", deployment=deployment)
    if tokens:
        out["tokens"] = int(sum(tokens.values()))
    return out


def _history_deltas(window_s: float):
    """Windowed per-series deltas of the request counter from the
    head's signal-plane history ring — zero sleeps; returns
    ``(deltas, actual_window_s)`` or ``(None, 0.0)`` when no ring is
    reachable (local backend, signal plane disabled, or the ring
    hasn't two samples yet)."""
    from ray_tpu._private import worker as _worker

    try:
        backend = _worker.backend()
    except Exception:
        return None, 0.0
    if backend is None or not hasattr(backend, "query_metrics"):
        return None, 0.0
    try:
        res = backend.query_metrics(
            {"op": "series_delta",
             "name": "ray_tpu_serve_requests_total",
             "window_s": float(window_s)})
    except Exception:
        return None, 0.0
    if not isinstance(res, dict) or not res.get("ok"):
        return None, 0.0
    actual = float(res.get("window_s") or 0.0)
    if actual <= 0:
        return None, 0.0
    series = {tuple(tuple(kv) for kv in labels): float(v)
              for labels, v in (res.get("series") or [])}
    return {"ray_tpu_serve_requests_total": series}, actual


def stats(window_s: float = 0.0, allow_sleep: bool = True) -> dict:
    """Per-deployment serving stats (``serve.stats()`` / ``ray-tpu serve
    stats`` / dashboard ``/api/serve_stats``): replica counts from the
    controller's routing table joined with p50/p99/mean, status counts,
    shed counts and live gauges from the metrics plane. With
    ``window_s > 0`` the head's signal-plane history ring answers the
    windowed ``qps`` / ``window_count`` deltas with ZERO sleeps; only
    off-cluster (local backend, ring disabled) does the old
    sleep-between-two-scrapes fallback run — and callers in a request
    path (the single-threaded dashboard) pass ``allow_sleep=False`` to
    skip the window instead of stalling."""
    import ray_tpu
    from ray_tpu.serve import _private as sp

    # A stats read must NOT spawn a controller on a cluster that never
    # used serve (same contract as the dashboard's GET routes).
    try:
        controller = ray_tpu.get_actor(sp.CONTROLLER_NAME)
    except ValueError:
        controller = None
    table = {}
    if controller is not None:
        _, table = ray_tpu.get(controller.get_routing_table.remote(),
                               timeout=30)
    text0 = metrics_text()
    parsed = parse_prometheus(text0)
    deltas: Optional[dict] = None
    window_used = 0.0
    if window_s and window_s > 0:
        deltas, window_used = _history_deltas(window_s)
        if deltas is None and allow_sleep:
            time.sleep(window_s)
            parsed_after = parse_prometheus(metrics_text())
            deltas = diff_parsed(parsed, parsed_after)
            parsed = parsed_after
            window_used = float(window_s)
    deployments = {}
    names = set(table) | set(
        sum_counter(parsed, "ray_tpu_serve_requests_total", "deployment"))
    for name in sorted(n for n in names if n):
        entry = deployment_stats(parsed, name)
        if name in table:
            entry["replicas"] = len(table[name]["replicas"])
            entry["max_concurrent_queries"] = \
                table[name]["max_concurrent_queries"]
            entry["route_prefix"] = table[name]["route_prefix"]
        if deltas is not None and window_used > 0:
            done = sum(sum_counter(
                deltas, "ray_tpu_serve_requests_total", "deployment",
                deployment=name).values())
            entry["qps"] = round(done / window_used, 2)
            entry["window_count"] = int(done)
        deployments[name] = entry
    out = {"deployments": deployments}
    rec = parsed.get("ray_tpu_serve_reconcile_seconds")
    if rec:
        out["reconcile_s"] = round(max(rec.values()), 6)
    return out
