"""Serve internals: controller, replicas, router, proxy, batching.

Reference parity (SURVEY.md §3.5):
  * control plane — detached ``ServeController`` actor reconciling
    deployment goal states into replica actors: rolling updates, dead
    replicas replaced, queue-depth autoscaling between min/max replicas
    (``serve/controller.py:61``, ``_private/deployment_state.py:958``,
    ``_private/autoscaling_policy.py``);
  * config fanout — routers/handles hold a blocking ``listen_for_change``
    long-poll on the controller and are PUSHED new routing tables the
    moment the version bumps — no polling sleeps on the request path
    (``_private/long_poll.py:68,185``);
  * data plane — ``Router`` with power-of-two-choices replica selection
    bounded by ``max_concurrent_queries`` (``_private/router.py:221,261``),
    replicas executing ``handle_request`` (``_private/replica.py:174``);
  * HTTP ingress — an asyncio server speaking an ASGI-style app interface,
    routing by longest path prefix (``_private/http_proxy.py:218``);
  * ``@serve.batch`` dynamic batching (``serve/batching.py``).
"""

from __future__ import annotations

import asyncio
import math
import os
import random
import threading
import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve import _observability as _obs
from ray_tpu.serve._observability import RequestShedError
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import tracing

CONTROLLER_NAME = "ray_tpu.serve.controller"
# One reconcile pass every interval: health checks, autoscale decisions,
# replica replacement.
RECONCILE_INTERVAL_S = 0.25
LONG_POLL_TIMEOUT_S = 10.0
# Consecutive failed health probes after which a replica is declared
# wedged (deadlocked, not just saturated) and replaced. With the 10s
# shared probe budget this is ~50s of continuous unresponsiveness.
# Saturation alone cannot trip this: replicas run with +1 executor
# thread of headroom reserved for probes (see _make_replica), so a miss
# means the process can't even answer a trivial call for ~10s — user
# code holding the GIL or a true deadlock, not just long requests.
_WEDGED_PROBE_FAILURES = 5


# -- replica ---------------------------------------------------------------


class Replica:
    """Actor wrapping one copy of the user's deployment callable."""

    def __init__(self, cls_or_fn, init_args, init_kwargs,
                 deployment_name: Optional[str] = None):
        if isinstance(cls_or_fn, type):
            self.callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.callable = cls_or_fn
        if deployment_name and hasattr(self.callable,
                                       "set_deployment_name"):
            # Callables that self-report metrics (the LLM engine's
            # decode families) need THIS deployment's name as their
            # label, or the stats join misses them under any name the
            # user didn't also pass into the bind args.
            try:
                self.callable.set_deployment_name(deployment_name)
            except Exception:
                pass
        self.num_ongoing = 0
        self._lock = threading.Lock()
        # Stable per-replica metrics label: pid is unique per node and
        # replicas are one actor per worker process; the id() suffix
        # disambiguates the in-process replicas of the local backend.
        self._replica_tag = f"{os.getpid()}-{id(self) & 0xFFFF:x}"

    def _target(self, method: str):
        return (self.callable if method == "__call__"
                else getattr(self.callable, method))

    def handle_request(self, method: str, args: tuple, kwargs: dict,
                       request_meta: Optional[dict] = None):
        """Execute one routed request.

        ``request_meta`` (set by ``routed_call``) carries the serve
        request context: deployment name, router enqueue timestamp
        (queue_wait = now - enqueue_ts covers the RPC + this replica's
        ongoing queue), the absolute deadline, and the trace context.
        Instrumented requests return a ``{"__serve_envelope__": ...}``
        dict so the replica-side phase breakdown rides back to the
        router with the result; meta-less direct calls keep the legacy
        bare-result shape. Controller health/autoscaling probes use
        ``get_num_ongoing``/``check_health`` and never pass through
        here, so they cannot pollute the request metrics."""
        if request_meta is None:
            with self._lock:
                self.num_ongoing += 1
            try:
                return self._target(method)(*args, **kwargs)
            finally:
                with self._lock:
                    self.num_ongoing -= 1

        dep = request_meta.get("deployment", "")
        now = time.time()
        queue_wait = max(0.0, now - request_meta.get("enqueue_ts", now))
        deadline_ts = request_meta.get("deadline_ts")
        if deadline_ts is not None and now > deadline_ts:
            # Arrived already expired (queued behind slow requests past
            # its budget): shed instead of executing dead work.
            _obs.record_shed(dep, "replica")
            return {"__serve_envelope__": 1, "shed": "replica",
                    "phases": {"queue_wait": queue_wait}}
        trace_ctx = request_meta.get("trace_ctx")
        if trace_ctx:
            tracing.enable()  # the caller traces: continue here
        span_cm = (tracing.span(
            f"serve.replica:{dep}.{method}",
            {"deployment": dep, "replica": self._replica_tag,
             "queue_wait_ms": round(queue_wait * 1e3, 3)},
            parent=trace_ctx, cat="serve")
            if trace_ctx and tracing.is_enabled() else nullcontext())
        # Gauge emits happen INSIDE the lock: counter capture and
        # publish must be atomic, or two concurrent completions can
        # publish out of order and strand the gauge at a stale nonzero
        # value on an idle replica.
        with self._lock:
            self.num_ongoing += 1
            _obs.set_replica_ongoing(dep, self._replica_tag,
                                     self.num_ongoing)
        try:
            # The scope carries the CALLER's span context (the stream/
            # route span that covers the whole request), not the replica
            # span just opened: the engine's queue/prefill/decode spans
            # outlive this handler call by the stream's whole life, and
            # critical-path extraction clips children to their parent's
            # interval — parenting them under a span that ends at
            # llm_submit-return would zero them out.
            with span_cm, _obs.request_scope(dep, deadline_ts,
                                             trace_ctx=trace_ctx):
                t_exec = time.time()
                try:
                    result = self._target(method)(*args, **kwargs)
                except RequestShedError as e:
                    # The @serve.batch queue shed this item (counted at
                    # the shed site); report it up as a shed envelope so
                    # the router raises a typed 503, not a user error.
                    return {"__serve_envelope__": 1,
                            "shed": getattr(e, "reason", "batch"),
                            "phases": {"queue_wait": queue_wait}}
                execute = time.time() - t_exec
        finally:
            with self._lock:
                self.num_ongoing -= 1
                _obs.set_replica_ongoing(dep, self._replica_tag,
                                         self.num_ongoing)
        phases = {"queue_wait": queue_wait, "execute": execute}
        _obs.record_phases(dep, phases)
        return {"__serve_envelope__": 1, "result": result,
                "phases": phases, "replica": self._replica_tag}

    def get_num_ongoing(self) -> int:
        return self.num_ongoing

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def check_health(self) -> str:
        return "ok"


# -- controller ------------------------------------------------------------


class ServeController:
    """Detached actor: goal-state reconciliation for all deployments.

    A background loop (``DeploymentState.update`` analog) continuously:
      * health-checks replicas and REPLACES dead ones,
      * applies queue-depth autoscaling between min/max replicas,
      * pushes any change to long-polling routers via ``listen_for_change``.
    """

    def __init__(self):
        # name -> {"replicas": [handles], goal state, autoscaling state}
        self.apps: Dict[str, dict] = {}
        self.config_version = 0
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._stop = False
        # node_id -> {"handle", "port"}; goal set by ensure_proxies and
        # maintained by the reconcile loop (http_state.py:30 analog).
        self._proxies: Dict[str, dict] = {}
        self._proxy_goal: Optional[dict] = None
        # Serializes whole reconcile passes (the loop vs. concurrent
        # ensure_proxies actor calls): check-then-create outside it would
        # double-start proxies and leak the losers.
        self._proxy_pass_lock = threading.Lock()
        threading.Thread(target=self._reconcile_loop, daemon=True).start()

    # -- goal-state writes --------------------------------------------------

    def deploy(self, name: str, cls_or_fn, init_args, init_kwargs,
               num_replicas: int, max_concurrent_queries: int,
               route_prefix: Optional[str], version: Optional[str],
               ray_actor_options: Optional[dict],
               autoscaling_config: Optional[dict] = None):
        """Create/update a deployment; rolling replace on redeploy."""
        auto = None
        if autoscaling_config is not None:
            auto = {
                "min_replicas": 1,
                "max_replicas": 8,
                "target_ongoing_requests": 2.0,
                "downscale_delay_s": 5.0,
                **autoscaling_config,
            }
            num_replicas = max(num_replicas, auto["min_replicas"])
        app = {
            "name": name,
            "route_prefix": route_prefix,
            "num_replicas": num_replicas,  # current target
            "max_concurrent_queries": max_concurrent_queries,
            "version": version or "1",
            "replicas": [],
            # Creation recipe — the reconcile loop uses it to start
            # replacement/scale-up replicas at any later time.
            "factory": (cls_or_fn, init_args, init_kwargs,
                        dict(ray_actor_options or {}), max_concurrent_queries),
            "autoscaling": auto,
            "last_high_demand_ts": time.monotonic(),
        }
        new_replicas = [self._start_replica(app) for _ in range(num_replicas)]
        # Verify the first replica constructed (fail fast on bad ctor) —
        # and never leak the batch if it didn't.
        try:
            ray_tpu.get(new_replicas[0].check_health.remote(), timeout=60)
        except Exception:
            for r in new_replicas:
                self._kill_replica(r)
            raise
        app["replicas"] = new_replicas

        with self._lock:
            existing = self.apps.get(name)
            old = existing["replicas"] if existing else []
            self.apps[name] = app
            self._bump_locked()
        # Rolling replace: retire old replicas after the new set is live.
        for r in old:
            self._kill_replica(r)
        return self.config_version

    def _start_replica(self, app: dict):
        cls_or_fn, init_args, init_kwargs, opts, max_q = app["factory"]
        replica_cls = ray_tpu.remote(Replica)
        opts = dict(opts)
        opts.setdefault("num_cpus", 0)
        # +1 thread of headroom so controller health probes are never
        # starved behind a fully saturated request queue.
        opts["max_concurrency"] = max(2, max_q) + 1
        return replica_cls.options(**opts).remote(
            cls_or_fn, init_args, init_kwargs, app["name"]
        )

    @staticmethod
    def _kill_replica(handle):
        try:
            ray_tpu.kill(handle)
        except Exception:
            pass

    def delete_deployment(self, name: str):
        with self._lock:
            app = self.apps.pop(name, None)
            if app:
                self._bump_locked()
        if app:
            for r in app["replicas"]:
                self._kill_replica(r)
        return True

    def _bump_locked(self):
        self.config_version += 1
        self._cv.notify_all()

    # -- reconcile loop ------------------------------------------------------

    def _reconcile_loop(self):
        while not self._stop:
            time.sleep(RECONCILE_INTERVAL_S)
            # Suppress tracing for the whole pass: health probes and
            # autoscaling fan out actor calls every 250ms — with tracing
            # enabled they would flood the span store and the timeline
            # with control-plane noise that is not user traffic.
            t0 = time.monotonic()
            with tracing.suppressed():
                try:
                    self._reconcile_once()
                except Exception:
                    # next tick retries; the loop must never die
                    _metrics.count_loop_restart("serve.reconcile")
                try:
                    self._reconcile_proxies()
                except Exception:
                    _metrics.count_loop_restart("serve.reconcile")
            try:
                _obs.record_reconcile(time.monotonic() - t0)
            except Exception:
                _metrics.count_loop_restart("serve.reconcile")

    def _reconcile_once(self):
        with self._lock:
            apps = list(self.apps.values())
        for app in apps:
            # 1. Probe replicas: liveness + in-flight depth in one call.
            #    All probes share one time budget so a single wedged
            #    replica can't stall repair of the others for 10s each.
            probes = [(r, r.get_num_ongoing.remote()) for r in app["replicas"]]
            deadline = time.monotonic() + 10.0
            alive, ongoing = [], []
            fails = app.setdefault("probe_failures", {})
            # Prune entries for replicas that left by scale-down/redeploy
            # (their miss counts would otherwise accumulate forever).
            current = {r._actor_id for r in app["replicas"]}
            for aid in [a for a in fails if a not in current]:
                del fails[aid]
            from ray_tpu.core.object_ref import ActorError

            # Every ref above is already in flight, so even a late get()
            # with a small residual timeout has given its probe the FULL
            # budget of wall-clock since issuance — a miss is ~10s of
            # unresponsiveness no matter where the replica sits in the list.
            for r, ref in probes:
                aid = r._actor_id
                try:
                    tmo = max(0.5, deadline - time.monotonic())
                    ongoing.append(float(ray_tpu.get(ref, timeout=tmo)))
                    alive.append(r)
                    fails.pop(aid, None)
                except ActorError:
                    self._kill_replica(r)  # actually dead: replace it
                    fails.pop(aid, None)
                except Exception:
                    # Slow/saturated probes merely queued behind real
                    # requests — keep the replica, treat as fully busy.
                    # But N consecutive misses = wedged (deadlocked user
                    # code): kill and replace.
                    fails[aid] = fails.get(aid, 0) + 1
                    if fails[aid] >= _WEDGED_PROBE_FAILURES:
                        self._kill_replica(r)
                        fails.pop(aid, None)
                    else:
                        alive.append(r)
                        ongoing.append(float(app["max_concurrent_queries"]))
            changed = len(alive) != len(app["replicas"])

            # 2. Autoscale: desired = ceil(total in-flight / target),
            #    clamped to [min, max]; downscale only after a sustained
            #    quiet period (autoscaling_policy.py behavior). Replicas
            #    can never carry more than max_concurrent_queries, so the
            #    effective per-replica target is capped there — and a
            #    fully saturated fleet scales up even though the queued
            #    demand behind the router cap is invisible to replicas.
            target = app["num_replicas"]
            auto = app["autoscaling"]
            if auto is not None:
                max_q = app["max_concurrent_queries"]
                eff_target = max(
                    1e-9, min(auto["target_ongoing_requests"], max_q))
                desired = math.ceil(sum(ongoing) / eff_target)
                if alive and all(o >= max_q for o in ongoing):
                    desired = max(desired, len(alive) + 1)
                desired = max(auto["min_replicas"],
                              min(auto["max_replicas"], desired))
                now = time.monotonic()
                if desired >= target:
                    app["last_high_demand_ts"] = now
                    target = desired
                elif now - app["last_high_demand_ts"] \
                        >= auto["downscale_delay_s"]:
                    target = desired
                app["num_replicas"] = target

            # 3. Converge replica count toward the target.
            started = []
            while len(alive) + len(started) < target:
                started.append(self._start_replica(app))
                changed = True
            while len(alive) > target:
                self._kill_replica(alive.pop())
                changed = True
            alive.extend(started)

            if changed:
                published = False
                with self._lock:
                    if self.apps.get(app["name"]) is app:
                        app["replicas"] = alive
                        self._bump_locked()
                        published = True
                if not published:
                    # Raced a redeploy/delete: this app dict is stale and
                    # replicas started for it would leak forever.
                    for r in started:
                        self._kill_replica(r)

    # -- per-node HTTP proxies (http_state.py:30 analog) ---------------------

    def ensure_proxies(self, host: str = "127.0.0.1") -> Dict[str, int]:
        """Goal-state write: one HTTPProxy actor on EVERY alive node,
        recreated by the reconcile loop when a proxy or its node dies —
        the reference starts an HTTPProxyActor per node the same way.
        Returns {node_id: port} (ports are ephemeral per proxy; a
        recreated proxy reports a fresh one via proxy_ports)."""
        with self._lock:
            self._proxy_goal = {"host": host}
        self._reconcile_proxies()
        return self.proxy_ports()

    def proxy_ports(self) -> Dict[str, int]:
        with self._lock:
            return {nid: p["port"] for nid, p in self._proxies.items()}

    def _reconcile_proxies(self):
        with self._proxy_pass_lock:
            self._reconcile_proxies_locked()

    def _reconcile_proxies_locked(self):
        with self._lock:
            goal = self._proxy_goal
            current = dict(self._proxies)
        if goal is None:
            return
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        alive = {n["NodeID"] for n in ray_tpu.nodes() if n["Alive"]}
        for nid in list(current):
            if nid not in alive:
                current.pop(nid, None)
                with self._lock:
                    self._proxies.pop(nid, None)
        for nid in sorted(alive):
            ent = current.get(nid)
            if ent is not None:
                try:
                    ray_tpu.get(ent["handle"].get_port.remote(), timeout=10)
                    continue  # healthy
                except Exception:
                    try:
                        ray_tpu.kill(ent["handle"])
                    except Exception:
                        pass
                    with self._lock:
                        self._proxies.pop(nid, None)
            proxy_cls = ray_tpu.remote(HTTPProxy)
            handle = proxy_cls.options(
                num_cpus=0, max_concurrency=16,
                scheduling_strategy=NodeAffinitySchedulingStrategy(nid),
            ).remote(goal["host"], 0)
            try:
                port = ray_tpu.get(handle.get_port.remote(), timeout=60)
            except Exception:
                self._kill_replica(handle)
                continue  # node may be going away; next tick retries
            with self._lock:
                self._proxies[nid] = {"handle": handle, "port": port}

    # -- config plane ---------------------------------------------------------

    def get_routing_table(self):
        """(version, {name: {replicas, max_concurrent_queries,
        route_prefix}}) for handles + proxies."""
        with self._lock:
            table = {
                name: {
                    "replicas": list(app["replicas"]),
                    "max_concurrent_queries": app["max_concurrent_queries"],
                    "route_prefix": app["route_prefix"],
                }
                for name, app in self.apps.items()
            }
            return self.config_version, table

    def listen_for_change(self, cur_version: int,
                          timeout: float = LONG_POLL_TIMEOUT_S):
        """Long-poll: block until config_version > cur_version (or
        timeout), then return the fresh routing table — config is PUSHED
        to routers, never polled per-request (long_poll.py:68,185)."""
        with self._cv:
            self._cv.wait_for(
                lambda: self.config_version > cur_version, timeout)
        return self.get_routing_table()

    def status(self):
        with self._lock:
            return {
                name: {
                    "num_replicas": app["num_replicas"],
                    "version": app["version"],
                    "route_prefix": app["route_prefix"],
                }
                for name, app in self.apps.items()
            }

    def shutdown_all(self):
        self._stop = True
        for name in list(self.apps):
            self.delete_deployment(name)
        with self._lock:
            proxies, self._proxies = dict(self._proxies), {}
            self._proxy_goal = None
        for ent in proxies.values():
            try:
                ray_tpu.get(ent["handle"].stop.remote(), timeout=5)
            except Exception:
                pass
            self._kill_replica(ent["handle"])
        return True


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    controller_cls = ray_tpu.remote(ServeController)
    try:
        handle = controller_cls.options(
            name=CONTROLLER_NAME, num_cpus=0, max_concurrency=64
        ).remote()
        ray_tpu.get(handle.status.remote(), timeout=30)
        return handle
    except ValueError:
        return ray_tpu.get_actor(CONTROLLER_NAME)


# -- router / handle --------------------------------------------------------


class _TableListener:
    """Shared long-poll client: a daemon thread blocks in the controller's
    ``listen_for_change`` and invokes ``apply_fn(version, table)`` on every
    push (used by Router and the HTTP proxy; long_poll.py:68 analog)."""

    def __init__(self, controller, apply_fn, current_version):
        self.controller = controller
        self._apply_fn = apply_fn
        self._current_version = current_version
        self.stopped = False
        with tracing.suppressed():  # config plane, not user traffic
            self._apply_fn(*ray_tpu.get(
                controller.get_routing_table.remote(), timeout=30))
        threading.Thread(target=self._loop, daemon=True).start()

    def refresh(self):
        """Synchronous out-of-band fetch (error-retry path)."""
        try:
            with tracing.suppressed():
                self._apply_fn(*ray_tpu.get(
                    self.controller.get_routing_table.remote(),
                    timeout=30))
        except Exception:
            pass

    def _loop(self):
        # Suppressed like the reconcile loop: a long-poll re-issued
        # every ~10s per router forever is config-plane traffic and
        # must not pollute request traces.
        while not self.stopped:
            try:
                with tracing.suppressed():
                    version, table = ray_tpu.get(
                        self.controller.listen_for_change.remote(
                            self._current_version()),
                        timeout=LONG_POLL_TIMEOUT_S + 30,
                    )
                self._apply_fn(version, table)
            except Exception:
                if self.stopped:
                    return
                _metrics.count_loop_restart("serve.table_listener")
                time.sleep(0.5)  # controller restarting; retry


class Router:
    """Power-of-two-choices replica selection with per-replica in-flight
    caps (client-side view of max_concurrent_queries).

    Routing-table updates are PUSHED via a ``_TableListener`` long-poll —
    ``assign`` never talks to the controller."""

    def __init__(self, controller, deployment_name: str):
        self.controller = controller
        self.name = deployment_name
        self._version = -1
        self._replicas: List = []
        self._max_q = 100
        # in-flight keyed by actor id so counts survive table swaps.
        self._inflight: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._known_name = False
        self._listener = _TableListener(
            controller, self._apply, lambda: self._version)
        if not self._known_name:
            self._listener.stopped = True
            raise ValueError(f"no deployment named {self.name!r}")

    @property
    def _stopped(self):
        return self._listener.stopped

    @_stopped.setter
    def _stopped(self, value):
        self._listener.stopped = value

    def _apply(self, version: int, table: dict):
        entry = table.get(self.name)
        self._known_name = entry is not None
        with self._lock:
            if version <= self._version:
                return
            self._version = version
            if entry is None:
                self._replicas = []
                return
            self._replicas = list(entry["replicas"])
            self._max_q = entry["max_concurrent_queries"]
            live = {r._actor_id for r in self._replicas}
            self._inflight = {
                aid: n for aid, n in self._inflight.items() if aid in live
            }

    def refresh(self):
        self._listener.refresh()

    def assign(self, exclude: Optional[set] = None,
               deadline_ts: Optional[float] = None):
        """Pick a replica, skipping ``exclude``d actor ids (known-dead from
        a failed attempt). Blocks while all candidates are saturated;
        raises :class:`RequestShedError` the moment ``deadline_ts``
        (absolute ``time.time()``) expires — a request whose budget died
        waiting for capacity must be shed, not executed late."""
        deadline = time.monotonic() + 60.0
        waiting = False
        try:
            while True:
                if deadline_ts is not None and time.time() > deadline_ts:
                    _obs.record_shed(self.name, "router")
                    raise RequestShedError(
                        f"deadline expired while waiting for a replica "
                        f"of {self.name!r}", reason="router")
                with self._lock:
                    pool = self._replicas
                    if exclude:
                        filtered = [r for r in pool
                                    if r._actor_id not in exclude]
                        # All known-dead: fall back to the full set and
                        # let the retry loop wait for the controller's
                        # replacement.
                        pool = filtered or pool
                    n = len(pool)
                    if n:
                        cands = [pool[0]] if n == 1 \
                            else random.sample(pool, 2)
                        best = min(
                            cands,
                            key=lambda r: self._inflight.get(
                                r._actor_id, 0))
                        aid = best._actor_id
                        if self._inflight.get(aid, 0) < self._max_q:
                            self._inflight[aid] = \
                                self._inflight.get(aid, 0) + 1
                            return aid, best
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"no replica of {self.name!r} available "
                        f"(backpressure)"
                    )
                if not waiting:
                    # Queued demand invisible to replicas (the router cap
                    # holds it here): export the depth while we wait.
                    waiting = True
                    _obs.router_queue_delta(self.name, +1)
                time.sleep(0.002)
        finally:
            if waiting:
                _obs.router_queue_delta(self.name, -1)

    def complete(self, aid: str):
        with self._lock:
            if self._inflight.get(aid, 0) > 0:
                self._inflight[aid] -= 1


# Per-process router cache, shared by handles and proxies.
_routers: Dict[str, Router] = {}
_routers_lock = threading.Lock()


def _router_for(name: str) -> Router:
    # Hot path: a cached router is returned with no controller RPC; stale
    # routers (from before a serve restart in a long-lived worker) are
    # evicted by _drop_router on routed_call's terminal failure.
    with _routers_lock:
        router = _routers.get(name)
    if router is not None:
        return router
    controller = get_or_create_controller()
    with _routers_lock:
        router = _routers.get(name)
        if router is None:
            router = _routers[name] = Router(controller, name)
        return router


def _drop_router(name: str, router: Router) -> None:
    with _routers_lock:
        if _routers.get(name) is router:
            router._stopped = True
            del _routers[name]


def reset_routers() -> None:
    """Stop long-poll threads and drop cached routers (serve.shutdown)."""
    with _routers_lock:
        for r in _routers.values():
            r._stopped = True
        _routers.clear()
    with _stream_tables_lock:
        _stream_tables.clear()


def routed_call(deployment_name: str, method: str, args: tuple, kwargs: dict,
                request_meta: Optional[dict] = None):
    """Route one request with retry-on-replica-death: a request that lands
    on a replica retired by a rolling update refreshes the routing table
    and retries elsewhere (the handle-side retry of the reference router).

    The request-path instrumentation lives here: one ``serve.route``
    span covering assign -> replica -> response (parented on the
    caller's trace context, so ingress -> router -> replica -> nested
    handle calls share one trace id across processes), the per-phase
    latency histogram (route / queue_wait / execute / serialize /
    total), the per-request status counter, and the deadline shed
    (:class:`RequestShedError` — mapped to HTTP 503 by the proxy)."""
    from ray_tpu.core.object_ref import ActorError

    meta = dict(request_meta or {})
    meta["deployment"] = deployment_name
    deadline_ts = meta.get("deadline_ts")
    trace_parent = meta.get("trace_ctx")
    if trace_parent:
        tracing.enable()  # the caller traces: continue here
    t0 = time.time()
    # Span only when the REQUEST carries trace context (same guard as
    # the replica): tracing.enable() above ratchets the process-global
    # flag, and gating on is_enabled() alone would make one traced
    # request flip this router into recording a root span for every
    # untraced request thereafter — flooding the head's span ring.
    span_cm = (tracing.span(
        f"serve.route:{deployment_name}",
        {"deployment": deployment_name, "method": method},
        parent=trace_parent, cat="serve")
        if trace_parent and tracing.is_enabled() else nullcontext())
    try:
        with span_cm as route_span:
            if route_span is not None:
                # The replica parents its span under the route span —
                # the serve trace context rides the request meta, not
                # the task spec, so it survives thread-pool hops (HTTP
                # proxy executor) and actor-call boundaries alike.
                meta["trace_ctx"] = {"trace_id": route_span["trace_id"],
                                     "span_id": route_span["span_id"]}
            router = _router_for(deployment_name)
            last_err = None
            dead: set = set()
            # route = time actually spent in assign, ACCUMULATED across
            # attempts — a dead-replica retry must not fold the failed
            # attempt's RPC time + backoff into the route histogram
            # (PROFILE.md reads "growing route" as a capacity signal;
            # retry losses land in the serialize remainder instead).
            route_s = 0.0
            for attempt in range(4):
                t_assign = time.time()
                aid, replica = router.assign(
                    exclude=dead, deadline_ts=deadline_ts)
                route_s += time.time() - t_assign
                meta["enqueue_ts"] = time.time()
                # A deadline bounds the IN-FLIGHT call too (+5s grace
                # for the response to ship): a replica wedged behind a
                # partition must not hold a deadlined request for the
                # full 120s — the caller gets a timely typed shed even
                # though the dispatched work itself cannot be recalled.
                rpc_timeout = 120.0
                if deadline_ts is not None:
                    rpc_timeout = max(
                        0.5, min(120.0, deadline_ts - time.time() + 5.0))
                try:
                    resp = ray_tpu.get(
                        replica.handle_request.remote(
                            method, args, kwargs, meta),
                        timeout=rpc_timeout,
                    )
                except TimeoutError:
                    if deadline_ts is None or time.time() < deadline_ts:
                        raise
                    _obs.record_shed(deployment_name, "inflight")
                    raise RequestShedError(
                        f"deadline expired while the request to "
                        f"{deployment_name!r} was in flight",
                        reason="inflight")
                except ActorError as e:
                    last_err = e
                    dead.add(aid)
                    # Back off so the controller's reconcile tick
                    # (0.25s) can replace the dead replica before we
                    # run out of attempts.
                    time.sleep(0.2 * (attempt + 1))
                    router.refresh()
                    continue
                finally:
                    router.complete(aid)
                return _finish_routed(
                    deployment_name, resp, t0, route_s)
            # Terminal failure: the router (and possibly its controller)
            # may be stale from before a serve restart — evict so the
            # next call rebuilds against the live controller.
            _drop_router(deployment_name, router)
            raise last_err
    except RequestShedError:
        _obs.record_status(deployment_name, "shed")
        raise
    except BaseException:
        _obs.record_status(deployment_name, "error")
        raise


def _finish_routed(deployment_name: str, resp, t0: float, route_s: float):
    """Unwrap the replica envelope; record the request's phase breakdown
    and terminal status (this is the single place every routed request
    passes exactly once)."""
    replica_phases: dict = {}
    if isinstance(resp, dict) and resp.get("__serve_envelope__"):
        shed = resp.get("shed")
        if shed:
            raise RequestShedError(
                f"request to {deployment_name!r} shed: deadline expired "
                f"at {shed}", reason=shed)
        replica_phases = resp.get("phases") or {}
        result = resp.get("result")
    else:  # legacy replica without envelope support
        result = resp
    total = time.time() - t0
    accounted = route_s + sum(
        replica_phases.get(p, 0.0) for p in ("queue_wait", "execute"))
    # Router-side phases ONLY: the replica already observed
    # queue_wait/execute (attributed to ITS node) when it ran the
    # request — re-recording them here would double-count. The
    # serialize remainder is the response's serialize/transfer/
    # deserialize path (the worker stores+ships the envelope after
    # execute returns).
    _obs.record_phases(deployment_name, {
        "route": route_s,
        "total": total,
        "serialize": max(0.0, total - accounted),
    })
    _obs.record_status(deployment_name, "ok")
    return result


# -- token streaming (LLM engine protocol) ----------------------------------

# Streaming replica table: deployment -> (fetched_at, [replica actor
# ids]). stream_call runs OUTSIDE the router (it must work from the
# ray:// proxy process, whose global backend is not the cluster's), so
# it resolves replicas straight off the controller with a short TTL
# cache — one controller round trip per deployment per TTL, not per
# stream.
_STREAM_TABLE_TTL_S = 2.0
_stream_tables: Dict[str, tuple] = {}
_stream_tables_lock = threading.Lock()

# Long-poll budget per llm_next call; the outer RPC timeout adds slack
# so a partitioned replica fails the stream FAST (typed, bounded by
# _STREAM_POLL_S + _STREAM_RPC_SLACK_S), never hangs it.
_STREAM_POLL_S = 1.0
_STREAM_RPC_SLACK_S = 25.0


def _stream_replicas(backend, deployment: str,
                     refresh: bool = False) -> List[str]:
    now = time.monotonic()
    with _stream_tables_lock:
        ent = _stream_tables.get(deployment)
        if ent and not refresh and now - ent[0] < _STREAM_TABLE_TTL_S:
            return ent[1]
    controller_id = backend.get_named_actor(CONTROLLER_NAME)
    with tracing.suppressed():
        [ref] = backend.submit_actor_task(
            controller_id, "get_routing_table", (), {})
        _, table = backend.get([ref], timeout=30.0)[0]
    entry = table.get(deployment)
    if entry is None:
        raise ValueError(f"no deployment named {deployment!r}")
    replicas = [r._actor_id for r in entry["replicas"]]
    if not replicas:
        raise RuntimeError(f"deployment {deployment!r} has no replicas")
    with _stream_tables_lock:
        _stream_tables[deployment] = (now, replicas)
    return replicas


def _stream_rpc(backend, actor_id: str, method: str, args: tuple,
                kwargs: dict, meta: Optional[dict], timeout: float):
    [ref] = backend.submit_actor_task(
        actor_id, "handle_request", (method, args, kwargs, meta), {})
    return backend.get([ref], timeout=timeout)[0]


# Sentinel frame the ray:// proxy interleaves on idle poll rounds so a
# deep-queued stream (TTFT = minutes) keeps its client socket alive;
# ClientBackend.serve_stream filters it out.
STREAM_KEEPALIVE = {"__stream_keepalive__": True}


def stream_call(deployment_name: str, args: tuple, kwargs: dict,
                request_meta: Optional[dict] = None, backend=None,
                poll_s: float = _STREAM_POLL_S,
                keepalive_every: Optional[float] = None):
    """Route one STREAMING request: generator of token chunks.

    The replica's callable must speak the LLM engine protocol
    (``llm_submit`` -> stream id, ``llm_next`` -> chunk drain; see
    ``serve/llm_engine.py``). The stream pins to ONE replica for its
    whole life — the KV-cache slot lives there. Submit retries across
    replicas on a dead pick; a replica dying MID-stream fails the
    stream fast (the slot died with the worker), and a deadline that
    expires mid-decode surfaces as a typed :class:`RequestShedError`
    (reason=decode) shed by the engine at a step boundary.

    When the caller traces (``trace_ctx`` in the request meta), the
    whole stream is one ``serve.stream`` span: downstream hops — the
    replica's llm_submit span, the engine's queue/prefill/decode spans
    — re-parent under it, and the first real token stamps the
    client-observed TTFT on its attributes.

    ``backend`` defaults to this process's backend; the ``ray://``
    proxy passes its own ClusterBackend explicitly (its process-global
    backend belongs to the CLIENT side)."""
    meta = dict(request_meta or {})
    trace_parent = meta.get("trace_ctx")
    if trace_parent:
        tracing.enable()  # the caller traces: continue here
    if not (trace_parent and tracing.is_enabled()):
        yield from _stream_call_impl(deployment_name, args, kwargs, meta,
                                     backend, poll_s, keepalive_every)
        return
    # Manual span (start_span/finish_span): the generator frame
    # interleaves with the consumer's code on one thread, so a
    # context-manager span's thread-local restore order would corrupt
    # across yields (same rule as the asgi proxy's await points).
    span = tracing.start_span(
        f"serve.stream:{deployment_name}",
        {"deployment": deployment_name}, parent=trace_parent, cat="serve")
    if span is not None:
        meta["trace_ctx"] = {"trace_id": span["trace_id"],
                             "span_id": span["span_id"]}
    status = "OK"
    t0 = time.monotonic()
    first = True
    try:
        for chunk in _stream_call_impl(deployment_name, args, kwargs,
                                       meta, backend, poll_s,
                                       keepalive_every):
            if first and span is not None and not (
                    isinstance(chunk, dict)
                    and chunk.get("__stream_keepalive__")):
                span["attributes"]["ttft_s"] = round(
                    time.monotonic() - t0, 6)
                first = False
            yield chunk
    except BaseException as e:
        status = f"ERROR: {type(e).__name__}"
        raise
    finally:
        tracing.finish_span(span, status)


def _stream_call_impl(deployment_name: str, args: tuple, kwargs: dict,
                      request_meta: Optional[dict], backend,
                      poll_s: float, keepalive_every: Optional[float]):
    if backend is None:
        from ray_tpu._private import worker as _worker

        backend = _worker.backend()
    meta = dict(request_meta or {})
    meta["deployment"] = deployment_name
    deadline_ts = meta.get("deadline_ts")
    if deadline_ts is not None:
        # The engine owns mid-stream deadline semantics (shed at a step
        # boundary, slot freed); the submit's request meta keeps the
        # deadline too so an already-dead arrival sheds at the replica.
        kwargs = {**kwargs, "deadline_ts": deadline_ts}
    from ray_tpu.core.object_ref import ActorError, GetTimeoutError

    last_err: Optional[BaseException] = None
    resp = None
    aid = None
    for attempt in range(3):
        try:
            replicas = _stream_replicas(
                backend, deployment_name, refresh=attempt > 0)
            aid = replicas[random.randrange(len(replicas))]
            resp = _stream_rpc(backend, aid, "llm_submit", args, kwargs,
                               meta, timeout=60.0)
            break
        except (ValueError, RequestShedError):
            raise
        except GetTimeoutError:
            # The submit may have EXECUTED on a wedged replica — the
            # task layer's dup suppression covers retried pushes of the
            # same spec, but a fresh submit here would be a second
            # admission (orphaned stream holding a decode slot). Fail
            # the stream instead of guessing.
            raise
        except (ActorError, RuntimeError) as e:
            # Dead replica pick / empty table mid-replacement: the old
            # incarnation's engine state died with the worker, so a
            # resubmit cannot double-admit. Anything else propagates.
            last_err = e
            time.sleep(0.2 * (attempt + 1))
    else:
        raise last_err
    if isinstance(resp, dict) and resp.get("__serve_envelope__"):
        shed = resp.get("shed")
        if shed:
            raise RequestShedError(
                f"stream to {deployment_name!r} shed at admission",
                reason=shed)
        rid = resp.get("result")
    else:
        rid = resp
    last_yield = time.monotonic()
    while True:
        # Polls go meta-less (the legacy bare-result path): a long-poll
        # is transport, not a request — it must not enter the request
        # histograms or be shed by the replica's arrival check.
        r = _stream_rpc(backend, aid, "llm_next", (rid,),
                        {"timeout_s": poll_s}, None,
                        timeout=poll_s + _STREAM_RPC_SLACK_S)
        chunks = r.get("chunks") or ()
        for chunk in chunks:
            yield chunk
        if chunks:
            last_yield = time.monotonic()
        elif keepalive_every is not None \
                and time.monotonic() - last_yield >= keepalive_every:
            # Deep-queued stream: nothing to say yet, but the consumer's
            # transport (the ray:// proxy RPC) needs frames to not time
            # out while the request waits for a slot.
            yield STREAM_KEEPALIVE
            last_yield = time.monotonic()
        if r.get("done"):
            shed = r.get("shed")
            if shed:
                raise RequestShedError(
                    f"stream to {deployment_name!r} shed mid-decode",
                    reason=shed)
            err = r.get("error")
            if err:
                raise RuntimeError(
                    f"stream to {deployment_name!r} failed: {err}")
            return


class DeploymentHandle:
    """Python-level handle: ``handle.remote(...)`` / ``handle.method.remote``
    (reference ``serve/handle.py``). Requests go through a routing proxy
    task so callers get a plain ObjectRef while routing keeps retry
    semantics.

    ``handle.options(deadline_s=...)`` attaches a per-request SLO
    deadline that rides the request context: the router and the batch
    queue shed the request (``RequestShedError`` / HTTP 503) instead of
    executing it once the budget is spent. The deadline is an absolute
    ``time.time()`` compared on whichever host the request reaches —
    correct within one host, and within NTP skew (typically ms) across
    hosts; sub-skew deadlines on unsynchronized multi-host clusters
    will mis-shed. When tracing is enabled, the caller's active span
    context rides along too, so the whole routed request joins the
    caller's trace."""

    def __init__(self, deployment_name: str, method_name: str = "__call__",
                 deadline_s: Optional[float] = None):
        self.deployment_name = deployment_name
        self.method_name = method_name
        self.deadline_s = deadline_s

    _UNSET = object()

    def options(self, *, deadline_s: "Optional[float]" = _UNSET
                ) -> "DeploymentHandle":
        # Sentinel default: an explicit deadline_s=None CLEARS an
        # inherited deadline; omitting the argument keeps it.
        return DeploymentHandle(
            self.deployment_name, self.method_name,
            deadline_s=self.deadline_s
            if deadline_s is DeploymentHandle._UNSET else deadline_s)

    def _request_meta(self) -> Optional[dict]:
        meta: dict = {}
        if self.deadline_s is not None:
            meta["deadline_ts"] = time.time() + self.deadline_s
        if tracing.is_enabled():
            ctx = tracing.current_context()
            if ctx:
                meta["trace_ctx"] = ctx
        return meta or None

    def remote(self, *args, **kwargs):
        call = ray_tpu.remote(routed_call).options(num_cpus=0)
        return call.remote(self.deployment_name, self.method_name, args,
                           kwargs, self._request_meta())

    def stream(self, *args, **kwargs):
        """Token-streaming call path (LLM engine protocol): a generator
        of per-step token chunks. ``handle.options(deadline_s=...)``
        applies — the engine sheds the stream typed (reason=decode) at
        the next step boundary once the budget dies. Over a ``ray://``
        connection the chunks are forwarded by the client proxy's
        server-streaming RPC."""
        from ray_tpu._private import worker as _worker

        backend = _worker.backend()
        if hasattr(backend, "serve_stream"):  # ray:// client backend
            return backend.serve_stream(
                self.deployment_name, args, kwargs, self._request_meta())
        return stream_call(self.deployment_name, args, kwargs,
                           self._request_meta())

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, name,
                                deadline_s=self.deadline_s)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.method_name, self.deadline_s))


# -- HTTP proxy -------------------------------------------------------------


_REASONS = {200: "OK", 404: "Not Found", 500: "Internal Server Error",
            503: "Service Unavailable"}

# Per-request deadline header: milliseconds of budget from ingress; the
# proxy converts it to the absolute deadline that rides the request
# context through router and batch queue.
DEADLINE_HEADER = "x-serve-deadline-ms"
# Opt into the token-streaming lane (LLM engine protocol): the response
# becomes chunked-transfer ndjson — one {"tokens": [...]} line per
# engine chunk, then a {"done": true, ...} terminator.
STREAM_HEADER = "x-serve-stream"


def make_asgi_app():
    """The proxy's ASGI application: routes by longest matching
    ``route_prefix`` from the (long-poll-pushed) routing table, decodes a
    JSON body, and dispatches through the shared Router. The blocking
    replica RPC runs in a thread pool so the event loop keeps accepting
    connections (http_proxy.py:218 uvicorn/ASGI analog).

    Request-path observability at the ingress: a W3C ``traceparent``
    header joins the caller's distributed trace (one ``serve.http``
    span covers the whole request, parenting the route/replica spans),
    ``x-serve-deadline-ms`` arms the per-request deadline, and a shed
    request answers 503 with the shedding site."""
    import asyncio
    import json as _json
    from concurrent.futures import ThreadPoolExecutor

    controller = get_or_create_controller()
    pool = ThreadPoolExecutor(max_workers=32)
    state = {"version": -1, "routes": []}  # [(prefix, name)]
    state_lock = threading.Lock()

    def apply_table(version, table):
        routes = sorted(
            ((e["route_prefix"], name) for name, e in table.items()
             if e.get("route_prefix")),
            key=lambda p: -len(p[0]),
        )
        with state_lock:
            if version > state["version"]:
                state["version"] = version
                state["routes"] = routes

    listener = _TableListener(
        controller, apply_table, lambda: state["version"])

    def resolve(path: str):
        with state_lock:
            for prefix, name in state["routes"]:
                if path.startswith(prefix):
                    return name
        return None

    async def app(scope, receive, send):
        assert scope["type"] == "http"
        body = b""
        while True:
            event = await receive()
            body += event.get("body", b"")
            if not event.get("more_body"):
                break

        async def reply(status: int, payload):
            blob = _json.dumps(payload).encode()
            await send({
                "type": "http.response.start",
                "status": status,
                "headers": [(b"content-type", b"application/json"),
                            (b"content-length",
                             str(len(blob)).encode())],
            })
            await send({"type": "http.response.body", "body": blob})

        name = resolve(scope["path"])
        if name is None:
            await reply(404, {"error": f"no route for {scope['path']}"})
            return
        headers = {}
        for k, v in scope.get("headers") or ():
            try:
                headers[k.decode("latin-1").lower()] = v.decode("latin-1")
            except Exception:
                continue
        meta: dict = {}
        # An upstream traceparent joins the caller's trace ONLY when
        # the operator enabled tracing here (RAY_TPU_TRACING_ENABLED /
        # tracing.enable()): the sampling decision belongs to the
        # server — an unauthenticated header must not be able to
        # switch on process-wide span recording.
        parent = (tracing.parse_traceparent(headers.get("traceparent"))
                  if tracing.is_enabled() else None)
        if parent is not None:
            meta["trace_ctx"] = parent
        deadline_raw = headers.get(DEADLINE_HEADER)
        if deadline_raw is not None:
            try:
                meta["deadline_ts"] = (
                    time.time() + max(0.0, float(deadline_raw)) / 1e3)
            except ValueError:
                pass  # malformed budget: serve without a deadline
        # Manual (non-context-manager) span: it stays open across the
        # await below, and interleaved request coroutines on this one
        # event-loop thread would corrupt a thread-local span stack's
        # restore order. Created only for requests that CARRY a
        # traceparent (the route/replica guards mirror this): serving
        # traces follow the caller's sampling decision — a proxy whose
        # tracing flag got ratcheted on by one propagated request must
        # not start recording every untraced request.
        http_span = (tracing.start_span(
            f"serve.http:{scope['path']}",
            {"deployment": name, "path": scope["path"]},
            parent=parent, cat="serve")
            if parent is not None else None)
        if http_span is not None:
            meta["trace_ctx"] = {
                "trace_id": http_span["trace_id"],
                "span_id": http_span["span_id"]}
        status = "OK"
        try:
            payload = _json.loads(body) if body else None
            loop = asyncio.get_running_loop()
            if headers.get(STREAM_HEADER):
                # Token-streaming lane: ndjson chunks over chunked
                # transfer encoding. The blocking stream generator runs
                # on a pool thread feeding an asyncio queue; the FIRST
                # event decides the status line, so a stream shed at
                # admission still answers a clean 503 instead of a 200
                # that dies mid-body.
                q: asyncio.Queue = asyncio.Queue()

                def pump():
                    try:
                        for chunk in stream_call(
                                name, (payload,), {}, meta or None):
                            loop.call_soon_threadsafe(
                                q.put_nowait, ("chunk", chunk))
                        loop.call_soon_threadsafe(
                            q.put_nowait, ("end", None))
                    except RequestShedError as e:
                        loop.call_soon_threadsafe(
                            q.put_nowait,
                            ("shed", getattr(e, "reason", "deadline")))
                    except BaseException as e:  # noqa: BLE001
                        loop.call_soon_threadsafe(
                            q.put_nowait, ("error", repr(e)))

                # Dedicated thread per stream, NOT the shared executor:
                # a pump blocks for the stream's whole life (minutes in
                # a deep admission queue), and 32 concurrent streams on
                # the 32-worker pool would wedge every non-streaming
                # request behind them.
                threading.Thread(target=pump, daemon=True).start()
                kind, val = await q.get()
                if kind == "shed":
                    status = "ERROR: RequestShedError"
                    await reply(503, {"error": "stream shed",
                                      "shed": val})
                    return
                if kind == "error":
                    status = "ERROR: stream"
                    await reply(500, {"error": val})
                    return
                await send({
                    "type": "http.response.start",
                    "status": 200,
                    "headers": [
                        (b"content-type", b"application/x-ndjson"),
                        (b"transfer-encoding", b"chunked")],
                })
                while True:
                    if kind == "chunk":
                        await send({
                            "type": "http.response.body",
                            "body": _json.dumps(
                                {"tokens": val}).encode() + b"\n",
                            "more_body": True})
                    else:
                        tail: dict = {"done": True}
                        if kind == "shed":
                            tail["shed"] = val
                            status = "ERROR: RequestShedError"
                        elif kind == "error":
                            tail["error"] = val
                            status = "ERROR: stream"
                        await send({
                            "type": "http.response.body",
                            "body": _json.dumps(tail).encode() + b"\n",
                            "more_body": False})
                        return
                    kind, val = await q.get()
            result = await loop.run_in_executor(
                pool, routed_call, name, "__call__", (payload,), {},
                meta or None)
            await reply(200, result)
        except RequestShedError as e:
            status = "ERROR: RequestShedError"
            await reply(503, {"error": str(e),
                              "shed": getattr(e, "reason", "deadline")})
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            status = f"ERROR: {type(e).__name__}"
            await reply(500, {"error": repr(e)})
        finally:
            tracing.finish_span(http_span, status)

    app.table_listener = listener  # so the proxy can stop it
    return app


class HTTPProxy:
    """Actor hosting an asyncio HTTP/1.1 server that drives the ASGI app
    above — connections multiplex on one event loop; only replica RPCs
    occupy pool threads."""

    def __init__(self, host: str, port: int):
        import asyncio

        self._app = make_asgi_app()
        self._loop = asyncio.new_event_loop()
        started = threading.Event()
        holder: dict = {}

        def run_loop():
            asyncio.set_event_loop(self._loop)

            async def boot():
                server = await asyncio.start_server(
                    self._handle_conn, host, port)
                holder["port"] = server.sockets[0].getsockname()[1]
                holder["server"] = server
                started.set()

            self._loop.run_until_complete(boot())
            self._loop.run_forever()

        threading.Thread(target=run_loop, daemon=True).start()
        if not started.wait(30):
            raise RuntimeError("HTTP proxy failed to start")
        self.port = holder["port"]
        self._server = holder["server"]

    async def _handle_conn(self, reader, writer):
        try:
            while True:
                request_line = await reader.readline()
                if not request_line or request_line == b"\r\n":
                    break
                method, path, _ = request_line.decode().split(" ", 2)
                headers = {}
                while True:
                    line = await reader.readline()
                    if line in (b"\r\n", b"", b"\n"):
                        break
                    k, _, v = line.decode().partition(":")
                    headers[k.strip().lower()] = v.strip()
                length = int(headers.get("content-length") or 0)
                body = await reader.readexactly(length) if length else b""

                scope = {
                    "type": "http",
                    "method": method,
                    "path": path.split("?")[0],
                    "headers": [(k.encode(), v.encode())
                                for k, v in headers.items()],
                }
                received = {"done": False}

                async def receive():
                    if received["done"]:
                        return {"type": "http.disconnect"}
                    received["done"] = True
                    return {"type": "http.request", "body": body,
                            "more_body": False}

                chunked = {"on": False}

                async def send(event):
                    if event["type"] == "http.response.start":
                        status = event["status"]
                        writer.write(
                            f"HTTP/1.1 {status} {_REASONS.get(status, 'OK')}"
                            "\r\n".encode())
                        for k, v in event.get("headers", []):
                            if (k.lower() == b"transfer-encoding"
                                    and v.lower() == b"chunked"):
                                chunked["on"] = True
                            writer.write(k + b": " + v + b"\r\n")
                        writer.write(b"\r\n")
                    elif event["type"] == "http.response.body":
                        body_bytes = event.get("body", b"")
                        if chunked["on"]:
                            # Chunked transfer framing: each body event
                            # ships as its own chunk so the client sees
                            # tokens as the engine produces them.
                            if body_bytes:
                                writer.write(
                                    f"{len(body_bytes):x}\r\n".encode()
                                    + body_bytes + b"\r\n")
                            if not event.get("more_body"):
                                writer.write(b"0\r\n\r\n")
                        else:
                            writer.write(body_bytes)
                        await writer.drain()

                await self._app(scope, receive, send)
                if headers.get("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def get_port(self) -> int:
        return self.port

    def stop(self):
        self._app.table_listener.stopped = True
        self._loop.call_soon_threadsafe(self._server.close)
        self._loop.call_soon_threadsafe(self._loop.stop)
        return True


# -- dynamic batching -------------------------------------------------------


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        # (arg, event, result_box, enqueue wall-ts, request ctx or None)
        self.items: list = []
        self.cv = threading.Condition()
        threading.Thread(target=self._loop, daemon=True).start()

    def submit(self, arg):
        event = threading.Event()
        box: list = [None, None]  # [value, error]
        # The serve request context (deployment + absolute deadline) is
        # captured HERE, on the request's own thread — the batch loop
        # thread has no contextvars of its own.
        ctx = _obs.current_request()
        with self.cv:
            self.items.append((arg, event, box, time.time(), ctx))
            self.cv.notify()
        event.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def _loop(self):
        while True:
            with self.cv:
                while not self.items:
                    self.cv.wait()
                deadline = time.monotonic() + self.timeout
                while (len(self.items) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self.cv.wait(max(0.0, deadline - time.monotonic()))
                batch = self.items[: self.max_batch_size]
                del self.items[: self.max_batch_size]
            # Shed items whose request deadline expired while they sat
            # in the queue: executing them would spend batch capacity on
            # work whose caller already gave up (503 at the boundary).
            now = time.time()
            run = []
            for item in batch:
                ctx = item[4]
                dl = ctx.get("deadline_ts") if ctx else None
                if dl is not None and now > dl:
                    dep = ctx.get("deployment", "") if ctx else ""
                    _obs.record_shed(dep, "batch")
                    item[2][1] = RequestShedError(
                        "deadline expired in the batch queue",
                        reason="batch")
                    item[1].set()
                else:
                    run.append(item)
            if not run:
                continue
            dep = next((it[4]["deployment"] for it in run if it[4]), "")
            _obs.record_batch(dep, len(run))
            for item in run:
                _obs.record_phases(
                    item[4]["deployment"] if item[4] else dep or "",
                    {"batch_wait": max(0.0, now - item[3])})
            args = [b[0] for b in run]
            try:
                results = self.fn(args)
                if len(results) != len(args):
                    raise ValueError(
                        f"batched fn returned {len(results)} results for "
                        f"{len(args)} inputs"
                    )
                for (_, event, box, _, _), r in zip(run, results):
                    box[0] = r
                    event.set()
            except BaseException as e:  # noqa: BLE001 — fan the error out
                _metrics.count_loop_restart("serve.batch_queue")
                for _, event, box, _, _ in run:
                    box[1] = e
                    event.set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: calls taking one item each are transparently
    batched into one call of the wrapped list->list function."""

    def wrap(fn):
        queue_holder: dict = {}
        lock = threading.Lock()

        def single(*args):
            # Methods: args = (self, item); functions: (item,).
            if len(args) == 2:
                self_obj, item = args
                key = id(self_obj)
                bound = lambda items: fn(self_obj, items)
            elif len(args) == 1:
                item = args[0]
                key = 0
                bound = fn
            else:
                raise TypeError("@serve.batch functions take exactly one item")
            with lock:
                q = queue_holder.get(key)
                if q is None:
                    q = queue_holder[key] = _BatchQueue(
                        bound, max_batch_size, batch_wait_timeout_s
                    )
            return q.submit(item)

        single.__name__ = getattr(fn, "__name__", "batched")
        return single

    if _fn is not None:
        return wrap(_fn)
    return wrap
