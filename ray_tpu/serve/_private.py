"""Serve internals: controller, replicas, router, proxy, batching.

Reference parity (SURVEY.md §3.5):
  * control plane — detached ``ServeController`` actor reconciling
    deployment goal states into replica actors with rolling updates
    (``serve/controller.py:61``, ``_private/deployment_state.py:958``);
  * data plane — ``Router`` with power-of-two-choices replica selection
    bounded by ``max_concurrent_queries`` (``_private/router.py:221,261``),
    replicas executing ``handle_request`` (``_private/replica.py:174``);
  * config fanout — handles refresh their replica view from the
    controller on a version change (the long-poll analog,
    ``_private/long_poll.py``);
  * HTTP ingress — a proxy actor running a threaded HTTP server that
    routes by prefix (``_private/http_proxy.py:312``);
  * ``@serve.batch`` dynamic batching (``serve/batching.py``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "ray_tpu.serve.controller"


# -- replica ---------------------------------------------------------------


class Replica:
    """Actor wrapping one copy of the user's deployment callable."""

    def __init__(self, cls_or_fn, init_args, init_kwargs):
        if isinstance(cls_or_fn, type):
            self.callable = cls_or_fn(*init_args, **init_kwargs)
        else:
            self.callable = cls_or_fn
        self.num_ongoing = 0
        self._lock = threading.Lock()

    def handle_request(self, method: str, args: tuple, kwargs: dict):
        with self._lock:
            self.num_ongoing += 1
        try:
            target = (
                self.callable if method == "__call__"
                else getattr(self.callable, method)
            )
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self.num_ongoing -= 1

    def get_num_ongoing(self) -> int:
        return self.num_ongoing

    def reconfigure(self, user_config):
        if hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def check_health(self) -> str:
        return "ok"


# -- controller ------------------------------------------------------------


class ServeController:
    """Detached actor: goal-state reconciliation for all deployments."""

    def __init__(self):
        # name -> {"deployment": info dict, "replicas": [handles],
        #          "version": int}
        self.apps: Dict[str, dict] = {}
        self.config_version = 0

    def deploy(self, name: str, cls_or_fn, init_args, init_kwargs,
               num_replicas: int, max_concurrent_queries: int,
               route_prefix: Optional[str], version: Optional[str],
               ray_actor_options: Optional[dict]):
        """Create/update a deployment; rolling replace on version change."""
        existing = self.apps.get(name)
        replica_cls = ray_tpu.remote(Replica)
        opts = dict(ray_actor_options or {})
        opts.setdefault("num_cpus", 0)
        opts["max_concurrency"] = max(2, max_concurrent_queries)

        new_replicas = []
        for _ in range(num_replicas):
            new_replicas.append(
                replica_cls.options(**opts).remote(
                    cls_or_fn, init_args, init_kwargs
                )
            )
        # Verify the first replica constructed (fail fast on bad ctor).
        ray_tpu.get(new_replicas[0].check_health.remote(), timeout=60)

        old = existing["replicas"] if existing else []
        self.apps[name] = {
            "name": name,
            "route_prefix": route_prefix,
            "num_replicas": num_replicas,
            "max_concurrent_queries": max_concurrent_queries,
            "version": version or "1",
            "replicas": new_replicas,
        }
        self.config_version += 1
        # Rolling replace: retire old replicas after the new set is live.
        for r in old:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        return self.config_version

    def delete_deployment(self, name: str):
        app = self.apps.pop(name, None)
        if app:
            for r in app["replicas"]:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
            self.config_version += 1
        return True

    def get_routing_table(self):
        """(version, {name: {replicas, max_concurrent_queries,
        route_prefix}}) for handles + proxies."""
        table = {
            name: {
                "replicas": app["replicas"],
                "max_concurrent_queries": app["max_concurrent_queries"],
                "route_prefix": app["route_prefix"],
            }
            for name, app in self.apps.items()
        }
        return self.config_version, table

    def status(self):
        return {
            name: {
                "num_replicas": app["num_replicas"],
                "version": app["version"],
                "route_prefix": app["route_prefix"],
            }
            for name, app in self.apps.items()
        }

    def shutdown_all(self):
        for name in list(self.apps):
            self.delete_deployment(name)
        return True


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    controller_cls = ray_tpu.remote(ServeController)
    try:
        handle = controller_cls.options(
            name=CONTROLLER_NAME, num_cpus=0, max_concurrency=8
        ).remote()
        ray_tpu.get(handle.status.remote(), timeout=30)
        return handle
    except ValueError:
        return ray_tpu.get_actor(CONTROLLER_NAME)


# -- router / handle --------------------------------------------------------


class Router:
    """Power-of-two-choices replica selection with per-replica in-flight
    caps (client-side view of max_concurrent_queries)."""

    def __init__(self, controller, deployment_name: str,
                 refresh_interval: float = 0.5):
        self.controller = controller
        self.name = deployment_name
        self.refresh_interval = refresh_interval
        self._version = -1
        self._replicas: List = []
        self._max_q = 100
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._refresh(force=True)

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.refresh_interval:
            return
        self._last_refresh = now
        version, table = ray_tpu.get(
            self.controller.get_routing_table.remote(), timeout=30
        )
        entry = table.get(self.name)
        if entry is None:
            raise ValueError(f"no deployment named {self.name!r}")
        if version != self._version:
            with self._lock:
                self._version = version
                self._replicas = list(entry["replicas"])
                self._max_q = entry["max_concurrent_queries"]
                self._inflight = {i: 0 for i in range(len(self._replicas))}

    def assign(self):
        """Pick a replica index (blocks while all are saturated)."""
        deadline = time.monotonic() + 60.0
        while True:
            self._refresh()
            with self._lock:
                n = len(self._replicas)
                if n:
                    if n == 1:
                        cands = [0]
                    else:
                        cands = random.sample(range(n), 2)
                    best = min(cands, key=lambda i: self._inflight.get(i, 0))
                    if self._inflight.get(best, 0) < self._max_q:
                        self._inflight[best] = self._inflight.get(best, 0) + 1
                        return best, self._replicas[best]
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self.name!r} available (backpressure)"
                )
            time.sleep(0.002)

    def complete(self, idx: int):
        with self._lock:
            if idx in self._inflight and self._inflight[idx] > 0:
                self._inflight[idx] -= 1


# Per-process router cache, shared by handles and proxies.
_routers: Dict[str, Router] = {}
_routers_lock = threading.Lock()


def _router_for(name: str) -> Router:
    with _routers_lock:
        router = _routers.get(name)
        if router is None:
            router = _routers[name] = Router(get_or_create_controller(), name)
        return router


def routed_call(deployment_name: str, method: str, args: tuple, kwargs: dict):
    """Route one request with retry-on-replica-death: a request that lands
    on a replica retired by a rolling update refreshes the routing table
    and retries elsewhere (the handle-side retry of the reference router)."""
    from ray_tpu.core.object_ref import ActorError

    router = _router_for(deployment_name)
    last_err = None
    for _ in range(4):
        idx, replica = router.assign()
        try:
            return ray_tpu.get(
                replica.handle_request.remote(method, args, kwargs),
                timeout=120.0,
            )
        except ActorError as e:
            last_err = e
            router._refresh(force=True)
            continue
        finally:
            router.complete(idx)
    raise last_err


class DeploymentHandle:
    """Python-level handle: ``handle.remote(...)`` / ``handle.method.remote``
    (reference ``serve/handle.py``). Requests go through a routing proxy
    task so callers get a plain ObjectRef while routing keeps retry
    semantics."""

    def __init__(self, deployment_name: str, method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.method_name = method_name

    def remote(self, *args, **kwargs):
        call = ray_tpu.remote(routed_call).options(num_cpus=0)
        return call.remote(self.deployment_name, self.method_name, args, kwargs)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, name)

    def __reduce__(self):
        return (DeploymentHandle, (self.deployment_name, self.method_name))


# -- HTTP proxy -------------------------------------------------------------


class HTTPProxy:
    """Actor hosting a threaded HTTP server; routes by path prefix."""

    def __init__(self, host: str, port: int):
        import http.server
        import json as _json

        controller = get_or_create_controller()

        def resolve(path: str):
            _, table = ray_tpu.get(
                controller.get_routing_table.remote(), timeout=30
            )
            best_name, best_prefix = None, ""
            for name, entry in table.items():
                prefix = entry.get("route_prefix")
                if prefix and path.startswith(prefix) and len(prefix) > len(best_prefix):
                    best_name, best_prefix = name, prefix
            return best_name

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _serve(self):
                try:
                    name = resolve(self.path)
                    if name is None:
                        self._reply(404, {"error": f"no route for {self.path}"})
                        return
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else b""
                    payload = _json.loads(body) if body else None
                    result = routed_call(name, "__call__", (payload,), {})
                    self._reply(200, result)
                except Exception as e:  # noqa: BLE001 — HTTP boundary
                    self._reply(500, {"error": repr(e)})

            def _reply(self, code: int, payload):
                blob = _json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            do_GET = _serve
            do_POST = _serve

            def log_message(self, *a):
                pass

        self.server = http.server.ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def get_port(self) -> int:
        return self.port

    def stop(self):
        self.server.shutdown()
        return True


# -- dynamic batching -------------------------------------------------------


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self.items: list = []  # (arg, event, result_box)
        self.cv = threading.Condition()
        threading.Thread(target=self._loop, daemon=True).start()

    def submit(self, arg):
        event = threading.Event()
        box: list = [None, None]  # [value, error]
        with self.cv:
            self.items.append((arg, event, box))
            self.cv.notify()
        event.wait()
        if box[1] is not None:
            raise box[1]
        return box[0]

    def _loop(self):
        while True:
            with self.cv:
                while not self.items:
                    self.cv.wait()
                deadline = time.monotonic() + self.timeout
                while (len(self.items) < self.max_batch_size
                       and time.monotonic() < deadline):
                    self.cv.wait(max(0.0, deadline - time.monotonic()))
                batch = self.items[: self.max_batch_size]
                del self.items[: self.max_batch_size]
            args = [b[0] for b in batch]
            try:
                results = self.fn(args)
                if len(results) != len(args):
                    raise ValueError(
                        f"batched fn returned {len(results)} results for "
                        f"{len(args)} inputs"
                    )
                for (_, event, box), r in zip(batch, results):
                    box[0] = r
                    event.set()
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for _, event, box in batch:
                    box[1] = e
                    event.set()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: calls taking one item each are transparently
    batched into one call of the wrapped list->list function."""

    def wrap(fn):
        queue_holder: dict = {}
        lock = threading.Lock()

        def single(*args):
            # Methods: args = (self, item); functions: (item,).
            if len(args) == 2:
                self_obj, item = args
                key = id(self_obj)
                bound = lambda items: fn(self_obj, items)
            elif len(args) == 1:
                item = args[0]
                key = 0
                bound = fn
            else:
                raise TypeError("@serve.batch functions take exactly one item")
            with lock:
                q = queue_holder.get(key)
                if q is None:
                    q = queue_holder[key] = _BatchQueue(
                        bound, max_batch_size, batch_wait_timeout_s
                    )
            return q.submit(item)

        single.__name__ = getattr(fn, "__name__", "batched")
        return single

    if _fn is not None:
        return wrap(_fn)
    return wrap
