"""Parallelism layer: device meshes, sharding rules, collective groups.

TPU-native replacement for the reference's ``ray.util.collective`` (group
management over NCCL/Gloo, ``python/ray/util/collective/collective.py``) and
for the parallelism strategies the reference lacks entirely (TP/PP/SP/EP —
see SURVEY.md §2.4): here they are named mesh axes over which XLA compiles
ICI collectives.
"""

from ray_tpu.parallel.mesh import (
    AXIS_ORDER,
    MeshConfig,
    auto_mesh_config,
    build_hybrid_mesh,
    build_mesh,
    local_device_count,
)
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_sharding,
    logical_spec,
    shard_pytree,
    with_logical_constraint,
)
from ray_tpu.parallel.pipeline import pipeline_apply, pipeline_apply_local

__all__ = [
    "pipeline_apply",
    "pipeline_apply_local",
    "AXIS_ORDER",
    "MeshConfig",
    "auto_mesh_config",
    "build_hybrid_mesh",
    "build_mesh",
    "local_device_count",
    "DEFAULT_RULES",
    "logical_sharding",
    "logical_spec",
    "shard_pytree",
    "with_logical_constraint",
]
