"""Device mesh construction with named parallelism axes.

The reference framework ships only data parallelism (torch DDP over NCCL,
``python/ray/train/torch/config.py:113``) and a collective-group API
(``python/ray/util/collective/collective.py:120``). Here *all* parallelism
strategies are axes of one `jax.sharding.Mesh`:

    pp    pipeline stages        (DCN-friendly, outermost)
    dp    pure data parallelism  (DCN-friendly)
    fsdp  data parallelism with sharded params/optimizer (ZeRO-3 style)
    sp    sequence/context parallelism (ring attention rides this axis)
    tp    tensor (Megatron-style) parallelism, innermost => fastest ICI hops
    ep    expert parallelism for MoE (aliased onto sp/tp-adjacent axis)

Axis order is chosen so that the innermost axes map to the
fastest-communicating device neighborhoods when `jax.make_mesh` lays devices
out (it uses the physical TPU topology); collectives over ``tp``/``sp`` then
ride short ICI rings while ``pp``/``dp`` tolerate DCN.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
from jax.sharding import Mesh

from ray_tpu._compat import AxisType, make_mesh

# Outermost -> innermost. ep shares the dims between sp and tp so MoE models
# can all_to_all over experts without a dedicated physical axis.
AXIS_ORDER: tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Product of all axes must equal device count.

    ``-1`` on at most one axis means "absorb all remaining devices"
    (same convention as a reshape wildcard).
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    # Explicit device list (for subsetting / tests); None = all devices.
    devices: Sequence[jax.Device] | None = None

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"Mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes


def local_device_count() -> int:
    return jax.local_device_count()


def auto_mesh_config(n_devices: int | None = None) -> MeshConfig:
    """Default config: pure fsdp (ZeRO-3 data parallelism) over every device.

    This is the safest high-performance default for dense LLM training at
    single-slice scale; callers opt into tp/sp/pp explicitly.
    """
    return MeshConfig(fsdp=n_devices if n_devices is not None else -1)


def build_mesh(
    config: MeshConfig | None = None,
    *,
    axis_types: AxisType = AxisType.Auto,
) -> Mesh:
    """Build a `jax.sharding.Mesh` with the standard axis names.

    Uses Auto axis types by default: shardings are propagated by XLA (GSPMD)
    from the in/out shardings and ``with_sharding_constraint`` hints, which is
    the idiomatic "annotate and let the compiler insert collectives" recipe.
    """
    config = config or auto_mesh_config()
    devices = list(config.devices) if config.devices is not None else jax.devices()
    sizes = config.axis_sizes(len(devices))
    mesh_devices = (
        make_mesh(
            tuple(sizes[a] for a in AXIS_ORDER),
            AXIS_ORDER,
            axis_types=(axis_types,) * len(AXIS_ORDER),
            devices=devices,
        )
    )
    return mesh_devices


def build_hybrid_mesh(
    per_slice: MeshConfig | None = None,
    *,
    dcn_dp: int | None = None,
    dcn_pp: int = 1,
    devices: Sequence[jax.Device] | None = None,
    axis_types: AxisType = AxisType.Auto,
) -> Mesh:
    """Multi-slice mesh: DCN between slices, ICI within (SURVEY §5.8).

    TPU pods beyond one slice have a two-tier network — fast ICI inside a
    slice, slower data-center network (DCN) between slices. The scaling
    recipe ("How to Scale Your Model"; jax ``mesh_utils.create_hybrid_
    device_mesh`` shape) is: put only DCN-tolerant axes across slices —
    pure data parallelism (``dcn_dp``: gradient all-reduce once per step)
    and/or pipeline stages (``dcn_pp``: point-to-point activations) — and
    keep tp/sp/fsdp collectives inside a slice.

    Devices are grouped by ``slice_index``; on hosts without one (CPU
    simulation, single slice) the device list is partitioned evenly into
    ``dcn_dp * dcn_pp`` synthetic slices so the layout is testable
    anywhere. ``per_slice`` shapes the ICI axes of one slice; the result
    is a standard AXIS_ORDER mesh whose ``dp``/``pp`` sizes are the
    DCN-times-ICI products.
    """
    import numpy as np

    devices = list(devices) if devices is not None else jax.devices()
    groups: dict[int, list] = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", 0) or 0, []).append(d)
    n_slices_wanted = (dcn_dp if dcn_dp is not None else
                       max(1, len(groups) // dcn_pp)) * dcn_pp
    if len(groups) == 1 and n_slices_wanted > 1:
        devs = next(iter(groups.values()))
        if len(devs) % n_slices_wanted:
            raise ValueError(
                f"{len(devs)} devices not divisible into "
                f"{n_slices_wanted} synthetic slices")
        per = len(devs) // n_slices_wanted
        groups = {i: devs[i * per:(i + 1) * per]
                  for i in range(n_slices_wanted)}
    slices = [groups[k] for k in sorted(groups)]
    num_slices = len(slices)
    if len({len(s) for s in slices}) != 1:
        raise ValueError("slices have unequal device counts")
    if dcn_dp is None:
        if num_slices % dcn_pp:
            raise ValueError(f"{num_slices} slices not divisible by "
                             f"dcn_pp={dcn_pp}")
        dcn_dp = num_slices // dcn_pp
    if dcn_dp * dcn_pp != num_slices:
        raise ValueError(
            f"dcn_dp({dcn_dp}) * dcn_pp({dcn_pp}) != slices({num_slices})")

    cfg = per_slice or MeshConfig(fsdp=-1)
    sizes = cfg.axis_sizes(len(slices[0]))
    # [dcn_pp, dcn_dp, pp, dp, fsdp, ep, sp, tp] — each slice keeps its
    # devices contiguous over the inner (ICI) dims.
    stacked = np.stack([
        np.array(s, dtype=object).reshape(
            [sizes[a] for a in AXIS_ORDER])
        for s in slices
    ]).reshape(dcn_pp, dcn_dp, *[sizes[a] for a in AXIS_ORDER])
    # Merge DCN dims into their ICI counterparts: pp-total outermost.
    stacked = np.moveaxis(stacked, 2, 1)  # [dcn_pp, pp, dcn_dp, dp, ...]
    final_shape = (
        dcn_pp * sizes["pp"], dcn_dp * sizes["dp"], sizes["fsdp"],
        sizes["ep"], sizes["sp"], sizes["tp"],
    )
    from ray_tpu._compat import mesh as _mesh

    return _mesh(stacked.reshape(final_shape), AXIS_ORDER,
                 axis_types=(axis_types,) * len(AXIS_ORDER))


def single_device_mesh() -> Mesh:
    """1-device mesh (all axes size 1) — lets model code be mesh-agnostic."""
    return build_mesh(MeshConfig(fsdp=1, devices=jax.devices()[:1]))
