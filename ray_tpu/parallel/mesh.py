"""Device mesh construction with named parallelism axes.

The reference framework ships only data parallelism (torch DDP over NCCL,
``python/ray/train/torch/config.py:113``) and a collective-group API
(``python/ray/util/collective/collective.py:120``). Here *all* parallelism
strategies are axes of one `jax.sharding.Mesh`:

    pp    pipeline stages        (DCN-friendly, outermost)
    dp    pure data parallelism  (DCN-friendly)
    fsdp  data parallelism with sharded params/optimizer (ZeRO-3 style)
    sp    sequence/context parallelism (ring attention rides this axis)
    tp    tensor (Megatron-style) parallelism, innermost => fastest ICI hops
    ep    expert parallelism for MoE (aliased onto sp/tp-adjacent axis)

Axis order is chosen so that the innermost axes map to the
fastest-communicating device neighborhoods when `jax.make_mesh` lays devices
out (it uses the physical TPU topology); collectives over ``tp``/``sp`` then
ride short ICI rings while ``pp``/``dp`` tolerate DCN.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import jax
from jax.sharding import AxisType, Mesh

# Outermost -> innermost. ep shares the dims between sp and tp so MoE models
# can all_to_all over experts without a dedicated physical axis.
AXIS_ORDER: tuple[str, ...] = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Product of all axes must equal device count.

    ``-1`` on at most one axis means "absorb all remaining devices"
    (same convention as a reshape wildcard).
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = -1
    ep: int = 1
    sp: int = 1
    tp: int = 1
    # Explicit device list (for subsetting / tests); None = all devices.
    devices: Sequence[jax.Device] | None = None

    def axis_sizes(self, n_devices: int) -> dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"At most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"Mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes


def local_device_count() -> int:
    return jax.local_device_count()


def auto_mesh_config(n_devices: int | None = None) -> MeshConfig:
    """Default config: pure fsdp (ZeRO-3 data parallelism) over every device.

    This is the safest high-performance default for dense LLM training at
    single-slice scale; callers opt into tp/sp/pp explicitly.
    """
    return MeshConfig(fsdp=n_devices if n_devices is not None else -1)


def build_mesh(
    config: MeshConfig | None = None,
    *,
    axis_types: AxisType = AxisType.Auto,
) -> Mesh:
    """Build a `jax.sharding.Mesh` with the standard axis names.

    Uses Auto axis types by default: shardings are propagated by XLA (GSPMD)
    from the in/out shardings and ``with_sharding_constraint`` hints, which is
    the idiomatic "annotate and let the compiler insert collectives" recipe.
    """
    config = config or auto_mesh_config()
    devices = list(config.devices) if config.devices is not None else jax.devices()
    sizes = config.axis_sizes(len(devices))
    mesh_devices = (
        jax.make_mesh(
            tuple(sizes[a] for a in AXIS_ORDER),
            AXIS_ORDER,
            axis_types=(axis_types,) * len(AXIS_ORDER),
            devices=devices,
        )
    )
    return mesh_devices


def single_device_mesh() -> Mesh:
    """1-device mesh (all axes size 1) — lets model code be mesh-agnostic."""
    return build_mesh(MeshConfig(fsdp=1, devices=jax.devices()[:1]))
