"""Pipeline parallelism over mesh axis ``pp``: GPipe forward streaming and
a 1F1B training schedule.

SURVEY.md §2.4 (absent from the reference, first-class here): layer stacks
shard over ``pp``; microbatches stream through the stages with
``ppermute`` forwarding activations stage->stage each tick; all devices
run the same program (SPMD), with stage identity = ``axis_index``.

Two schedules:

* ``pipeline_apply`` — forward-only GPipe streaming (inference / under
  plain autodiff, which replays the scan in reverse: GPipe-style training
  with all n_micro activations live).
* ``pipeline_value_and_grad`` — 1F1B (one-forward-one-backward): each tick
  a stage runs one microbatch forward AND one backward (vjp with
  rematerialized forward), with backward priority and a per-stage
  in-flight cap of pp - s. Activation memory is O(pp) microbatches per
  stage instead of GPipe's O(n_micro); stage inputs (not residuals) are
  saved, the stage forward recomputes inside the vjp. The fwd/bwd
  schedules are computed in Python (static for XLA) and streamed through
  one ``lax.scan``; activations ride a forward ``ppermute`` ring,
  cotangents a backward one.

Requirements: every stage maps activations [mb, ...] -> [mb, ...] of the
same shape (the transformer-block case), and stage parameters are a pytree
whose leaves have a leading ``pp``-sharded stage dimension.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from ray_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply_local(stage_params, x_micro, *, stage_fn: Callable,
                         axis: str = "pp", axis_size: int):
    """Per-device body (inside shard_map over ``axis``).

    stage_params: this stage's params (leading stage dim of size 1, squeezed
    here). x_micro: [n_micro, mb, ...] (replicated). Returns this device's
    per-tick outputs [n_ticks, mb, ...]; the caller extracts the last
    stage's valid ticks.
    """
    pp = axis_size
    s = jax.lax.axis_index(axis)
    params = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = x_micro.shape[0]
    n_ticks = n_micro + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        arriving = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x0 = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, 0, keepdims=False)
        inp = jnp.where(s == 0, x0, arriving)
        out = stage_fn(params, inp)
        sent = jax.lax.ppermute(out, axis, perm)
        return sent, out

    _, ys = jax.lax.scan(tick, jnp.zeros_like(x_micro[0]), jnp.arange(n_ticks))
    return ys[None]  # restore a device-stacked leading dim for out_specs


def pipeline_apply(stage_params, x, mesh: Mesh, *, stage_fn: Callable,
                   n_micro: int, axis: str = "pp"):
    """Run x [batch, ...] through the pp-sharded stage stack.

    stage_params: pytree with leading dim == mesh.shape[axis] (one slice
    per stage), sharded P(axis, ...). Returns [batch, ...] outputs.
    """
    pp = mesh.shape[axis]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into {n_micro} microbatches")
    x_micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stage_params
    )
    fn = shard_map(
        functools.partial(
            pipeline_apply_local, stage_fn=stage_fn, axis=axis, axis_size=pp
        ),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(axis),
        check_vma=False,
    )
    ys = fn(stage_params, x_micro)  # [pp, n_ticks, mb, ...]
    # Valid outputs: last stage (pp-1), ticks pp-1 .. pp-1+n_micro-1.
    outs = ys[pp - 1, pp - 1 : pp - 1 + n_micro]
    return outs.reshape(b, *x.shape[1:])


# ---------------------------------------------------------------------------
# 1F1B
# ---------------------------------------------------------------------------


def build_1f1b_schedule(n_micro: int, pp: int, style: str = "1f1b"):
    """Static pipeline timetable. Returns (fwd, bwd, fwd_arrive,
    bwd_arrive), each a [T, pp] int list: the microbatch index stage s
    handles (or receives) at tick t, -1 for idle.

    Rules (greedy, backward-priority — the canonical 1F1B shape):
      * stage s may forward mb i once stage s-1 forwarded it on an earlier
        tick (ppermute costs one tick); stage 0 is source-fed;
      * stage s may backward mb i once stage s+1 backwarded it on an
        earlier tick; the LAST stage may backward mb i on the same tick it
        forwards it (the fwd slot runs first within a tick);
      * in-flight forwards per stage are capped at pp - s (the 1F1B
        memory bound).

    ``style="gpipe"``: no in-flight cap, and backwards wait for EVERY
    forward to finish (all-fwd-then-all-bwd) — the schedule GPipe runs,
    with O(n_micro) live activations instead of 1F1B's O(pp). Kept for
    the pipeline microbenchmark and as the reference point the 1F1B
    memory claim is measured against.
    """
    f_time = [[None] * n_micro for _ in range(pp)]
    b_time = [[None] * n_micro for _ in range(pp)]
    f_next = [0] * pp
    b_next = [0] * pp
    fwd, bwd = [], []
    t = 0
    while any(b < n_micro for b in b_next):
        frow = [-1] * pp
        for s in range(pp):
            i = f_next[s]
            if i >= n_micro:
                continue
            if style == "1f1b" \
                    and f_next[s] - b_next[s] >= max(1, pp - s):
                continue  # 1F1B in-flight cap
            ready = (s == 0) or (
                f_time[s - 1][i] is not None and f_time[s - 1][i] < t)
            if ready:
                frow[s] = i
                f_time[s][i] = t
                f_next[s] += 1
        brow = [-1] * pp
        all_fwd_done = all(f >= n_micro for f in f_next)
        for s in range(pp):
            i = b_next[s]
            if i >= n_micro:
                continue
            if style == "gpipe" and not all_fwd_done:
                continue  # flush phase: backwards only after every fwd
            if s == pp - 1:
                ready = f_time[s][i] is not None and f_time[s][i] <= t
            else:
                ready = b_time[s + 1][i] is not None and b_time[s + 1][i] < t
            if ready:
                brow[s] = i
                b_time[s][i] = t
                b_next[s] += 1
        fwd.append(frow)
        bwd.append(brow)
        t += 1
        if t > 4 * (n_micro + pp) + 16:  # schedule bug guard
            raise AssertionError("1F1B schedule failed to converge")
    T = len(fwd)
    fwd_arrive = [
        [fwd[t - 1][s - 1] if t >= 1 and s >= 1 else -1 for s in range(pp)]
        for t in range(T)
    ]
    bwd_arrive = [
        [bwd[t - 1][s + 1] if t >= 1 and s < pp - 1 else -1
         for s in range(pp)]
        for t in range(T)
    ]
    return fwd, bwd, fwd_arrive, bwd_arrive


def _1f1b_local(stage_params, x_micro, y_micro, fwd_sched, bwd_sched,
                fwd_arrive, bwd_arrive, *, stage_fn: Callable,
                loss_fn: Callable, axis: str, axis_size: int,
                grad_psum_axes: tuple = (), save_slots: int = 0):
    """Per-device 1F1B body (inside shard_map over ``axis``).

    Every tick executes one (masked) stage forward AND one (masked)
    vjp-with-remat backward — SPMD: all devices run the same ops, validity
    comes from the schedule tables. Returns (loss contribution, this
    stage's param grads with the leading stage dim restored).
    """
    pp = axis_size
    s = jax.lax.axis_index(axis)
    params = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    T = fwd_sched.shape[0]
    is_last = s == pp - 1
    is_first = s == 0
    fperm = [(i, (i + 1) % pp) for i in range(pp)]
    bperm = [(i, (i - 1) % pp) for i in range(pp)]
    zero_act = jnp.zeros(act_shape, x_micro.dtype)
    buf0 = jnp.zeros((pp, *act_shape), x_micro.dtype)
    # Activation stash: pp slots suffice under the 1F1B in-flight cap
    # (THE memory win); a GPipe schedule keeps all n_micro alive.
    n_save = save_slots or pp
    saved0 = jnp.zeros((n_save, *act_shape), x_micro.dtype)

    def tick(carry, t):
        fwd_msg, bwd_msg, in_buf, gbuf, saved, gacc, loss_sum = carry
        # Deliver last tick's ppermute payloads into the mb-ring buffers.
        amb = fwd_arrive[t, s]
        in_buf = jnp.where(
            amb >= 0, in_buf.at[jnp.clip(amb, 0) % pp].set(fwd_msg), in_buf)
        gmb = bwd_arrive[t, s]
        gbuf = jnp.where(
            gmb >= 0, gbuf.at[jnp.clip(gmb, 0) % pp].set(bwd_msg), gbuf)

        # Forward slot.
        fmb = fwd_sched[t, s]
        fvalid = fmb >= 0
        fi = jnp.clip(fmb, 0)
        x_in = jnp.where(is_first, x_micro[fi], in_buf[fi % pp])
        out = stage_fn(params, x_in).astype(x_micro.dtype)
        saved = jnp.where(fvalid, saved.at[fi % n_save].set(x_in), saved)
        fwd_msg = jax.lax.ppermute(
            jnp.where(fvalid, out, zero_act), axis, fperm)

        # Backward slot: vjp with rematerialized forward. One vjp serves
        # every stage: the last stage pulls the cotangent out of the
        # per-microbatch loss (seed 1), earlier stages out of the incoming
        # activation cotangent (seed 0 on the loss output).
        bmb = bwd_sched[t, s]
        bvalid = bmb >= 0
        bi = jnp.clip(bmb, 0)
        x_saved = saved[bi % n_save]
        y_mb = jax.lax.dynamic_index_in_dim(y_micro, bi, 0, keepdims=False)

        def f(p, xx):
            o = stage_fn(p, xx)
            return o, loss_fn(o, y_mb)

        (o, l), vjp_fn = jax.vjp(f, params, x_saved)
        cot_o = jnp.where(is_last, jnp.zeros_like(o),
                          gbuf[bi % pp].astype(o.dtype))
        cot_l = jnp.where(is_last, jnp.ones((), l.dtype),
                          jnp.zeros((), l.dtype))
        dp, dx = vjp_fn((cot_o, cot_l))
        gacc = jax.tree.map(
            lambda a, g: a + jnp.where(bvalid, g, jnp.zeros_like(g)),
            gacc, dp)
        loss_sum = loss_sum + jnp.where(
            bvalid & is_last, l, jnp.zeros((), l.dtype))
        bwd_msg = jax.lax.ppermute(
            jnp.where(bvalid, dx.astype(x_micro.dtype), zero_act),
            axis, bperm)
        return (fwd_msg, bwd_msg, in_buf, gbuf, saved, gacc, loss_sum), None

    grad0 = jax.tree.map(jnp.zeros_like, params)
    init = (zero_act, zero_act, buf0, buf0, saved0, grad0,
            jnp.zeros((), jnp.float32))
    (_, _, _, _, _, gacc, loss_sum), _ = jax.lax.scan(
        tick, init, jnp.arange(T))
    # Mean-over-microbatches semantics for both value and grads.
    loss = jax.lax.psum(loss_sum, axis) / n_micro
    if grad_psum_axes:
        # Data-like in-stage axes (sp sequence shards, dp replicas inside
        # the stage): every param's grad is a partial sum over the tokens
        # that axis split — reduce it here, inside the shard_map, exactly
        # like the reference's grad allreduce over dp x sp. Params
        # SHARDED over one of these axes keep local grads (their tokens
        # are local by construction); callers pass only axes that shard
        # data, not params.
        # pmean, matching the mean-loss convention (loss_fn averages over
        # its LOCAL tokens; the global loss is the mean of shard means).
        gacc = jax.tree.map(
            lambda g: jax.lax.pmean(g, grad_psum_axes), gacc)
        loss = jax.lax.pmean(loss, grad_psum_axes)
    grads = jax.tree.map(lambda g: (g / n_micro)[None], gacc)
    return loss, grads


def pipeline_value_and_grad(stage_params, x, y, mesh: Mesh, *,
                            stage_fn: Callable, loss_fn: Callable,
                            n_micro: int, axis: str = "pp",
                            param_specs=None, data_spec=None,
                            grad_psum_axes: tuple = (),
                            style: str = "1f1b"):
    """1F1B training pass: returns (mean microbatch loss, d loss / d
    stage_params) for ``loss_fn(stage_fn(...last stage...), y)``.

    stage_params: pytree with leading dim == mesh.shape[axis]; x, y:
    [batch, ...] split into ``n_micro`` microbatches. ``param_specs``
    overrides the default ``P(axis, None, ...)`` sharding — pass specs
    naming other mesh axes (e.g. an expert axis) to combine pp with
    in-stage parallelism; collectives over those axes are legal inside
    ``stage_fn``.

    ``data_spec``: PartitionSpec for the POST-microbatching activations
    [n_micro, mb, ...] (and y), e.g. ``P(None, None, "sp")`` to run
    sequence-parallel ring attention inside each stage. Any axis that
    shards data this way must also appear in ``grad_psum_axes`` so param
    grads (partial sums over that axis's token shard) are reduced inside
    the shard_map — the dp x sp grad-allreduce of a classic trainer.
    """
    pp = mesh.shape[axis]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into {n_micro} microbatches")
    x_micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])
    y_micro = y.reshape(n_micro, b // n_micro, *y.shape[1:])
    fwd, bwd, f_arr, b_arr = build_1f1b_schedule(n_micro, pp, style)
    tables = tuple(
        jnp.asarray(a, jnp.int32) for a in (fwd, bwd, f_arr, b_arr))
    if param_specs is None:
        param_specs = jax.tree.map(
            lambda p: P(axis, *([None] * (p.ndim - 1))), stage_params)
    dspec = data_spec if data_spec is not None else P()
    fn = shard_map(
        functools.partial(
            _1f1b_local, stage_fn=stage_fn, loss_fn=loss_fn, axis=axis,
            axis_size=pp, grad_psum_axes=tuple(grad_psum_axes),
            save_slots=(pp if style == "1f1b" else n_micro),
        ),
        mesh=mesh,
        in_specs=(param_specs, dspec, dspec, P(), P(), P(), P()),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return fn(stage_params, x_micro, y_micro, *tables)
