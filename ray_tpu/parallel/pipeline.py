"""Pipeline parallelism: GPipe microbatch schedule over mesh axis ``pp``.

SURVEY.md §2.4 (absent from the reference, first-class here): layer stacks
shard over ``pp``; microbatches stream through the stages with
``ppermute`` forwarding activations stage->stage each tick. Total ticks =
n_micro + pp - 1 (the pipeline bubble); all devices run the same program
(SPMD), with stage identity = ``axis_index``.

Requirements: every stage maps activations [mb, ...] -> [mb, ...] of the
same shape (the transformer-block case), and stage parameters are a pytree
whose leaves have a leading ``pp``-sharded stage dimension.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply_local(stage_params, x_micro, *, stage_fn: Callable,
                         axis: str = "pp", axis_size: int):
    """Per-device body (inside shard_map over ``axis``).

    stage_params: this stage's params (leading stage dim of size 1, squeezed
    here). x_micro: [n_micro, mb, ...] (replicated). Returns this device's
    per-tick outputs [n_ticks, mb, ...]; the caller extracts the last
    stage's valid ticks.
    """
    pp = axis_size
    s = jax.lax.axis_index(axis)
    params = jax.tree.map(lambda p: p[0], stage_params)
    n_micro = x_micro.shape[0]
    n_ticks = n_micro + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        arriving = carry
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        x0 = jax.lax.dynamic_index_in_dim(x_micro, mb_idx, 0, keepdims=False)
        inp = jnp.where(s == 0, x0, arriving)
        out = stage_fn(params, inp)
        sent = jax.lax.ppermute(out, axis, perm)
        return sent, out

    _, ys = jax.lax.scan(tick, jnp.zeros_like(x_micro[0]), jnp.arange(n_ticks))
    return ys[None]  # restore a device-stacked leading dim for out_specs


def pipeline_apply(stage_params, x, mesh: Mesh, *, stage_fn: Callable,
                   n_micro: int, axis: str = "pp"):
    """Run x [batch, ...] through the pp-sharded stage stack.

    stage_params: pytree with leading dim == mesh.shape[axis] (one slice
    per stage), sharded P(axis, ...). Returns [batch, ...] outputs.
    """
    pp = mesh.shape[axis]
    b = x.shape[0]
    if b % n_micro:
        raise ValueError(f"batch {b} must divide into {n_micro} microbatches")
    x_micro = x.reshape(n_micro, b // n_micro, *x.shape[1:])

    param_specs = jax.tree.map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stage_params
    )
    fn = shard_map(
        functools.partial(
            pipeline_apply_local, stage_fn=stage_fn, axis=axis, axis_size=pp
        ),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(axis),
        check_vma=False,
    )
    ys = fn(stage_params, x_micro)  # [pp, n_ticks, mb, ...]
    # Valid outputs: last stage (pp-1), ticks pp-1 .. pp-1+n_micro-1.
    outs = ys[pp - 1, pp - 1 : pp - 1 + n_micro]
    return outs.reshape(b, *x.shape[1:])
