"""Logical-axis sharding rules (t5x/MaxText-style).

Model code names tensor dimensions logically ("batch", "embed", "mlp", ...);
a rules table maps logical names to physical mesh axes. Swapping parallelism
strategy = swapping the rules table, with no model changes — the TPU-native
answer to the reference's per-strategy wrapper libraries (SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# logical dim -> physical mesh axis (or tuple of axes, or None = replicated).
# Mirrors the MaxText/t5x convention.
DEFAULT_RULES: dict[str, tuple[str, ...] | None] = {
    "batch": ("dp", "fsdp"),
    "seq": ("sp",),  # activation sequence dim (context parallelism)
    "vocab": ("tp",),
    "embed": ("fsdp",),  # param hidden dim => ZeRO-3 sharding
    "mlp": ("tp",),
    "heads": ("tp",),
    "qkv": ("tp",),
    "kv_seq": ("sp",),
    "layers": ("pp",),  # stacked per-layer params; pp>1 shards stages
    "expert": ("ep",),
    None: None,
}


def logical_spec(
    logical_axes: Sequence[str | None],
    rules: Mapping[str, tuple[str, ...] | None] | None = None,
) -> PartitionSpec:
    """Translate logical dims to a PartitionSpec via the rules table.

    Each physical axis may be used at most once per spec; later logical dims
    that map to an already-used physical axis fall back to replicated — e.g.
    ('batch', 'seq', 'embed') -> PartitionSpec(('dp','fsdp'), 'sp', None)
    because 'batch' already consumed fsdp. This keeps one rules table valid
    for every tensor in the model.
    """
    rules = rules or DEFAULT_RULES
    used: set[str] = set()
    out: list[tuple[str, ...] | str | None] = []
    for name in logical_axes:
        axes = rules.get(name) if name is not None else None
        if axes is None:
            out.append(None)
            continue
        free = tuple(a for a in axes if a not in used)
        if not free:
            out.append(None)
            continue
        used.update(free)
        out.append(free if len(free) > 1 else free[0])
    return PartitionSpec(*out)


def logical_sharding(
    mesh: Mesh,
    logical_axes: Sequence[str | None],
    rules: Mapping[str, tuple[str, ...] | None] | None = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def with_logical_constraint(
    x: jax.Array,
    logical_axes: Sequence[str | None],
    mesh: Mesh | None = None,
    rules: Mapping[str, tuple[str, ...] | None] | None = None,
) -> jax.Array:
    """``with_sharding_constraint`` by logical names; no-op outside jit/mesh."""
    mesh = mesh or _current_mesh()
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, logical_spec(logical_axes, rules))
    )


def _current_mesh() -> Mesh | None:
    # Abstract mesh from the surrounding jit, if any.
    try:
        env = jax.sharding.get_abstract_mesh()
        if env is not None and not env.empty:
            return env
    except Exception:
        pass
    return None


def shard_pytree(tree, sharding_tree, mesh: Mesh):
    """device_put a pytree of host arrays onto the mesh per a sharding tree."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, sharding_tree)
