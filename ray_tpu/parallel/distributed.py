"""Multi-host JAX runtime bootstrap via the cluster KV.

The TPU-defining piece of the collective layer (SURVEY.md §7 step 6): a
group of worker processes (one per host) rendezvous through the cluster's
internal KV and call ``jax.distributed.initialize`` so that all hosts'
devices form ONE global mesh and jitted step functions run SPMD across
hosts with XLA collectives on ICI/DCN.

Reference pattern being replaced: NCCL-unique-id rendezvous via a named
actor (``python/ray/util/collective/collective_group/nccl_collective_group.py``
rendezvous) and rank-0 master addr/port fan-out in
``python/ray/train/torch/config.py:129-181``. Here the shared secret is the
coordinator address, published by rank 0 under ``jaxdist/<group>/coordinator``.

On real TPU pods each worker-host simply calls ``initialize()`` with its
rank; the CPU test path forces ``platform="cpu"`` with N virtual devices
per process (Gloo cross-process collectives), which is how multi-host
behavior is validated without a pod (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Optional

_KEY = "jaxdist/{group}/coordinator"


def host_ip() -> str:
    """Best-effort routable IP of this host (falls back to localhost)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))  # no packets sent; picks the route
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def publish_coordinator(group: str, address: Optional[str] = None) -> str:
    """Rank 0: publish the coordinator address in the cluster KV."""
    from ray_tpu.experimental import internal_kv

    if address is None:
        address = f"{host_ip()}:{free_port()}"
    internal_kv.kv_put(_KEY.format(group=group), address)
    return address


def wait_coordinator(group: str, timeout: float = 120.0) -> str:
    """Non-zero ranks: poll the KV until rank 0 publishes."""
    from ray_tpu.experimental import internal_kv

    deadline = time.monotonic() + timeout
    key = _KEY.format(group=group)
    while time.monotonic() < deadline:
        addr = internal_kv.kv_get(key)
        if addr is not None:
            return addr
        time.sleep(0.05)
    raise TimeoutError(f"no coordinator published for group {group!r}")


def clear_group(group: str) -> None:
    from ray_tpu.experimental import internal_kv

    internal_kv.kv_del(_KEY.format(group=group))


def initialize(
    group: str,
    rank: int,
    world_size: int,
    *,
    platform: Optional[str] = None,
    num_cpu_devices: Optional[int] = None,
    coordinator_address: Optional[str] = None,
    local_device_ids: Optional[list[int]] = None,
    timeout: float = 120.0,
) -> None:
    """Join the named process group and initialize the JAX runtime.

    Must run before any JAX backend touch in this process. ``platform`` /
    ``num_cpu_devices`` configure the CPU simulation path; on a real pod
    leave them None and the TPU runtime discovers topology itself.
    """
    import jax

    if platform is not None:
        os.environ["JAX_PLATFORMS"] = platform
        jax.config.update("jax_platforms", platform)
    if num_cpu_devices is not None:
        from ray_tpu._compat import set_num_cpu_devices

        set_num_cpu_devices(num_cpu_devices)

    if world_size == 1 and coordinator_address is None:
        return  # single-process: nothing to rendezvous

    if coordinator_address is None:
        if rank == 0:
            coordinator_address = publish_coordinator(group)
        else:
            coordinator_address = wait_coordinator(group, timeout)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=world_size,
        process_id=rank,
        local_device_ids=local_device_ids,
        initialization_timeout=int(timeout),
    )


def shutdown() -> None:
    import jax

    try:
        jax.distributed.shutdown()
    except (RuntimeError, ValueError):
        pass
