"""Distributed FIFO queue backed by an actor.

Reference parity: ``python/ray/util/queue.py`` — Queue with optional
``maxsize``, blocking put/get with timeouts, nowait variants, batch ops,
and ``Empty``/``Full`` exceptions re-exported.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


def driver_node_options() -> Optional[dict]:
    """``actor_options`` pinning a queue's actor to the DRIVER's node.

    The default zero-demand round-robin can land a results queue on any
    node — including one a drain/preemption is about to take — and a
    dead queue masquerades as a failure of every consumer wired to it
    (a trial that keeps "failing" with a drain-shaped cause retries
    exempt forever). The driver's node is the one node the consumer
    already cannot outlive; None on the local backend (placement is
    moot there)."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    node_id = getattr(worker_mod.backend(), "node_id", None)
    if node_id is None:
        return None
    return {"scheduling_strategy": NodeAffinitySchedulingStrategy(node_id)}


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: list = []

    def qsize(self) -> int:
        return len(self.items)

    def put_nowait(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def put_nowait_batch(self, items: list) -> bool:
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True

    def get_nowait(self):
        if not self.items:
            return False, None
        return True, self.items.pop(0)

    def get_nowait_batch(self, num_items: int):
        if len(self.items) < num_items:
            return False, None
        out = self.items[:num_items]
        del self.items[:num_items]
        return True, out


class Queue:
    """Actor-backed queue; handles are serializable and shareable."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        self.maxsize = maxsize
        opts = dict(actor_options or {})
        opts.setdefault("num_cpus", 0)
        self.actor = (
            ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)
        )

    def __reduce__(self):
        return (_rebuild_queue, (self.maxsize, self.actor))

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def _poll(self, op, timeout: float | None, err):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, value = op()
            if ok:
                return value
            if deadline is not None and time.monotonic() >= deadline:
                raise err
            time.sleep(0.005)

    def put(self, item, block: bool = True, timeout: float | None = None):
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # Ship the payload only when the queue looks acceptable; while
            # full, poll the cheap qsize probe instead of re-serializing the
            # item every tick.
            if ray_tpu.get(self.actor.put_nowait.remote(item)):
                return
            while (
                self.maxsize > 0
                and ray_tpu.get(self.actor.qsize.remote()) >= self.maxsize
            ):
                if deadline is not None and time.monotonic() >= deadline:
                    raise Full
                time.sleep(0.005)

    def put_nowait(self, item):
        self.put(item, block=False)

    def put_nowait_batch(self, items: List[Any]):
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full

    def get(self, block: bool = True, timeout: float | None = None):
        if not block:
            ok, value = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return value

        def op():
            return ray_tpu.get(self.actor.get_nowait.remote())

        return self._poll(op, timeout, Empty())

    def get_nowait(self):
        return self.get(block=False)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        ok, values = ray_tpu.get(self.actor.get_nowait_batch.remote(num_items))
        if not ok:
            raise Empty
        return values

    def shutdown(self):
        ray_tpu.kill(self.actor)


def _rebuild_queue(maxsize, actor):
    q = object.__new__(Queue)
    q.maxsize = maxsize
    q.actor = actor
    return q
