"""ParallelIterator: lazy sharded iteration over actors
(reference: ``python/ray/util/iter.py``).

    it = from_items([1, 2, 3, 4], num_shards=2)
    it = it.for_each(lambda x: x * 2).filter(lambda x: x > 2).batch(2)
    list(it.gather_sync())  # pulls round-robin from the shard actors

Shards are actors holding their slice; transformations accumulate into a
per-shard op pipeline applied actor-side (data stays put, functions move —
the reference's core design), and ``gather_sync`` streams results back in
shard round-robin order.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu

_DONE = "__parallel_iterator_exhausted__"


class _ShardActor:
    """One shard: items + the transformation pipeline, iterated lazily."""

    def __init__(self, items: list):
        self._items = items
        self._it = None

    def start(self, ops: list):
        def gen():
            for x in self._items:
                out = [x]
                for kind, fn in ops:
                    if kind == "for_each":
                        out = [fn(v) for v in out]
                    elif kind == "filter":
                        out = [v for v in out if fn(v)]
                    elif kind == "flatten":
                        out = [w for v in out for w in v]
                yield from out

        self._it = gen()
        return True

    def next_items(self, n: int):
        assert self._it is not None, "start() not called"
        out = []
        for _ in range(n):
            try:
                out.append(next(self._it))
            except StopIteration:
                return out, True
        return out, False


class ParallelIterator:
    def __init__(self, shards_items: List[list], ops: list | None = None,
                 batch_size: int | None = None):
        self._shards_items = shards_items
        self._ops = ops or []
        self._batch = batch_size

    # -- transformations (lazy, applied actor-side) -----------------------

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(
            self._shards_items, self._ops + [("for_each", fn)], self._batch)

    def filter(self, fn: Callable) -> "ParallelIterator":
        return ParallelIterator(
            self._shards_items, self._ops + [("filter", fn)], self._batch)

    def flatten(self) -> "ParallelIterator":
        return ParallelIterator(
            self._shards_items, self._ops + [("flatten", None)], self._batch)

    def batch(self, n: int) -> "ParallelIterator":
        return ParallelIterator(self._shards_items, list(self._ops), n)

    def num_shards(self) -> int:
        return len(self._shards_items)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._ops or other._ops:
            raise ValueError("union() must precede transformations")
        return ParallelIterator(
            self._shards_items + other._shards_items, [], self._batch)

    # -- consumption ------------------------------------------------------

    def gather_sync(self) -> Iterable[Any]:
        """Round-robin pull from shard actors until all are exhausted.
        One ``next_items`` request stays in flight PER live shard, so
        shard-side transformation work overlaps across actors while this
        consumer yields in deterministic round-robin order."""
        actor_cls = ray_tpu.remote(_ShardActor)
        actors = [actor_cls.remote(items) for items in self._shards_items]
        ray_tpu.get([a.start.remote(self._ops) for a in actors], timeout=60)
        pull = self._batch or 32
        inflight = [(a, a.next_items.remote(pull)) for a in actors]
        try:
            while inflight:
                next_round = []
                for a, ref in inflight:
                    items, done = ray_tpu.get(ref, timeout=60)
                    if not done:
                        # re-arm BEFORE yielding: the shard computes its
                        # next batch while the consumer processes this one
                        next_round.append((a, a.next_items.remote(pull)))
                    if self._batch:
                        if items:
                            yield items
                    else:
                        yield from items
                inflight = next_round
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def take(self, n: int) -> list:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)

    def __repr__(self) -> str:
        return (f"ParallelIterator[{len(self._shards_items)} shards, "
                f"{len(self._ops)} ops]")


def from_items(items: list, num_shards: int = 2) -> ParallelIterator:
    shards: List[list] = [[] for _ in range(num_shards)]
    for i, x in enumerate(items):
        shards[i % num_shards].append(x)
    return ParallelIterator(shards)


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)


def from_iterators(generators: List[Iterable]) -> ParallelIterator:
    return ParallelIterator([list(g) for g in generators])
