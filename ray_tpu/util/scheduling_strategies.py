"""Scheduling strategies (reference: ``python/ray/util/scheduling_strategies.py:15,41``).

A task/actor's ``scheduling_strategy`` option is either:
  * ``"DEFAULT"`` — hybrid policy (prefer local node, spill when saturated);
  * ``"SPREAD"`` — round-robin over feasible nodes;
  * ``PlacementGroupSchedulingStrategy`` — run inside a reserved bundle;
  * ``NodeAffinitySchedulingStrategy`` — pin to a node id (soft or hard).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

DEFAULT = "DEFAULT"
SPREAD = "SPREAD"


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "object"  # PlacementGroup (avoid import cycle)
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False

    def __post_init__(self):
        if not isinstance(self.node_id, str):
            raise TypeError("node_id must be a string")


VALID_STRING_STRATEGIES = (DEFAULT, SPREAD)


def validate_strategy(strategy) -> None:
    if strategy is None:
        return
    if isinstance(strategy, str):
        if strategy not in VALID_STRING_STRATEGIES:
            raise ValueError(
                f"invalid scheduling_strategy {strategy!r}; "
                f"expected one of {VALID_STRING_STRATEGIES} or a strategy object"
            )
        return
    if isinstance(
        strategy, (PlacementGroupSchedulingStrategy, NodeAffinitySchedulingStrategy)
    ):
        return
    raise TypeError(f"invalid scheduling_strategy: {strategy!r}")
