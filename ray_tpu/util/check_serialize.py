"""Serializability debugging (reference: ``python/ray/util/check_serialize.py``
``inspect_serializability``): when a task/actor argument fails to pickle,
walk its closure/attributes and name the exact offending members instead
of one opaque cloudpickle stack trace.

    ok, failures = inspect_serializability(obj)
    # failures: [FailureTuple(obj=<socket>, name="sock", parent=<A>)]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Set, Tuple

import cloudpickle


@dataclass
class FailureTuple:
    obj: Any
    name: str
    parent: Any

    def __repr__(self) -> str:
        return f"FailTuple({self.name} [obj={self.obj!r}, parent={self.parent!r}])"


def _serializable(obj: Any) -> bool:
    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _inspect(obj: Any, name: str, parent: Any, failures: list,
             seen: Set[int], depth: int, max_depth: int) -> None:
    if id(obj) in seen or depth > max_depth:
        return
    seen.add(id(obj))
    if _serializable(obj):
        return
    children: list[Tuple[str, Any]] = []
    # closures of functions
    closure = getattr(obj, "__closure__", None)
    if closure:
        names = getattr(obj.__code__, "co_freevars", ())
        children += [
            (names[i] if i < len(names) else f"cell{i}", c.cell_contents)
            for i, c in enumerate(closure)
            if c.cell_contents is not obj
        ]
    # globals a function captures
    if hasattr(obj, "__globals__") and hasattr(obj, "__code__"):
        g = obj.__globals__
        children += [
            (n, g[n]) for n in obj.__code__.co_names if n in g
        ]
    # instance / class attributes
    if hasattr(obj, "__dict__") and isinstance(getattr(obj, "__dict__"), dict):
        children += list(vars(obj).items())
    if isinstance(obj, dict):
        children += [(str(k), v) for k, v in obj.items()]
    elif isinstance(obj, (list, tuple, set)):
        children += [(f"[{i}]", v) for i, v in enumerate(obj)]

    found_deeper = False
    for child_name, child in children:
        if not _serializable(child):
            found_deeper = True
            _inspect(child, f"{name}.{child_name}", obj, failures, seen,
                     depth + 1, max_depth)
    if not found_deeper:
        # This object is the leaf cause.
        failures.append(FailureTuple(obj=obj, name=name, parent=parent))


def inspect_serializability(
    obj: Any, name: str | None = None, max_depth: int = 4,
    print_failures: bool = True,
) -> Tuple[bool, list]:
    """Returns (serializable, failures). Mirrors the reference signature;
    ``failures`` holds the deepest non-serializable members found."""
    name = name or getattr(obj, "__name__", type(obj).__name__)
    failures: list = []
    _inspect(obj, name, None, failures, set(), 0, max_depth)
    ok = not failures
    if print_failures and failures:
        print(f"{name} is not serializable. Offending members:")
        for f in failures:
            print(f"  {f}")
    return ok, failures
