"""ActorPool: load-balance tasks over a fixed set of actors.

Reference parity: ``python/ray/util/actor_pool.py`` — same surface
(map / map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / push / pop_idle).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def map(self, fn: Callable, values: Iterable):
        """Apply fn(actor, value) over values, yielding results in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def get_next(self, timeout: float | None = None):
        """Next result in submission order. A timeout leaves the pool
        untouched so the call can be retried."""
        from ray_tpu.core.object_ref import GetTimeoutError

        if not self.has_next():
            raise StopIteration("no more results to get")
        future = self._index_to_future[self._next_return_index]
        try:
            value = ray_tpu.get(future, timeout=timeout)
        except GetTimeoutError:
            raise  # task still running; state unchanged, retryable
        except Exception:
            self._consume(future)  # task finished (with an error)
            raise
        self._consume(future)
        return value

    def get_next_unordered(self, timeout: float | None = None):
        """Earliest-finishing result, any order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        ready, _ = ray_tpu.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("timed out waiting for a result")
        future = ready[0]
        try:
            value = ray_tpu.get(future)
        finally:
            self._consume(future)
        return value

    def _consume(self, future):
        i, actor = self._future_to_actor.pop(future)
        self._index_to_future.pop(i, None)
        # Ordered gets resume past everything consumed out of order
        # (reference behavior: mixing ordered/unordered skips indices).
        if i >= self._next_return_index:
            self._next_return_index = i + 1
        self._return_actor(actor)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def push(self, actor):
        """Add a new idle actor to the pool."""
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def pop_idle(self):
        """Remove and return an idle actor, or None if none are idle."""
        return self._idle.pop() if self.has_free() else None
