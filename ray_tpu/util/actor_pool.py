"""ActorPool: load-balance tasks over a fixed set of actors.

Reference parity: ``python/ray/util/actor_pool.py`` — same surface
(map / map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / push / pop_idle). Internals are queue-structured rather than
index-counted: submission order lives in one FIFO of futures that ordered
consumption drains (lazily skipping entries already taken out of order),
so there are no return-index bookkeeping counters to keep in sync.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, List

import ray_tpu


class ActorPool:
    def __init__(self, actors: List[Any]):
        self._idle = list(actors)
        # future -> the actor running it (membership = still in flight).
        self._actor_of: dict = {}
        # Futures in submission order; entries consumed unordered stay in
        # the deque and are skipped lazily when an ordered get reaches
        # them (reference behavior: mixing ordered/unordered gets skips
        # past results already taken).
        self._order: "collections.deque" = collections.deque()
        # Submissions waiting for an actor to free up.
        self._backlog: "collections.deque" = collections.deque()

    def map(self, fn: Callable, values: Iterable):
        """Apply fn(actor, value) over values, yielding results in order."""
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def submit(self, fn: Callable, value):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._actor_of[future] = actor
            self._order.append(future)
        else:
            self._backlog.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._actor_of) or bool(self._backlog)

    def _oldest_pending(self):
        """Front of the submission queue that is still in flight."""
        while self._order and self._order[0] not in self._actor_of:
            self._order.popleft()  # consumed unordered: skip
        return self._order[0] if self._order else None

    def get_next(self, timeout: float | None = None):
        """Next result in submission order. A timeout leaves the pool
        untouched so the call can be retried."""
        from ray_tpu.core.object_ref import GetTimeoutError

        if not self.has_next():
            raise StopIteration("no more results to get")
        future = self._oldest_pending()
        if future is None:
            # Backlogged work but nothing in flight: no actor can ever pick
            # it up (pool built with zero actors, or all were pop_idle'd).
            raise RuntimeError(
                f"ActorPool has {len(self._backlog)} queued submission(s) "
                "but no actors to run them; push() an actor first")
        try:
            value = ray_tpu.get(future, timeout=timeout)
        except GetTimeoutError:
            raise  # task still running; state unchanged, retryable
        except Exception:
            self._consume(future)  # task finished (with an error)
            raise
        self._consume(future)
        return value

    def get_next_ref(self, timeout: float | None = None):
        """Next result in submission order as an OBJECT REF, without
        fetching the value to this process (the dataset pool path keeps
        blocks in the store instead of round-tripping every block
        through driver memory). Waits for completion; a timeout leaves
        the pool untouched so the call can be retried."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        future = self._oldest_pending()
        if future is None:
            raise RuntimeError(
                f"ActorPool has {len(self._backlog)} queued submission(s) "
                "but no actors to run them; push() an actor first")
        ready, _ = ray_tpu.wait([future], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for a result")
        self._consume(future)
        return future

    def get_next_unordered(self, timeout: float | None = None):
        """Earliest-finishing result, any order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        if not self._actor_of:
            raise RuntimeError(
                f"ActorPool has {len(self._backlog)} queued submission(s) "
                "but no actors to run them; push() an actor first")
        ready, _ = ray_tpu.wait(
            list(self._actor_of), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("timed out waiting for a result")
        future = ready[0]
        try:
            value = ray_tpu.get(future)
        finally:
            self._consume(future)
        return value

    def _consume(self, future):
        actor = self._actor_of.pop(future)
        self._recycle(actor)

    def _recycle(self, actor):
        self._idle.append(actor)
        if self._backlog:
            fn, value = self._backlog.popleft()
            self.submit(fn, value)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._backlog

    def push(self, actor):
        """Add a new idle actor to the pool."""
        self._recycle(actor)

    def pop_idle(self):
        """Remove and return an idle actor, or None if none are idle."""
        return self._idle.pop() if self.has_free() else None


class AutoscalingActorPool(ActorPool):
    """ActorPool that grows on queue depth and shrinks on idle
    (reference ``data/_internal/compute.py:173`` ActorPoolStrategy
    semantics): starts at ``min_size`` actors, adds one whenever a
    submission finds no idle actor and the backlog has reached
    ``scale_up_queue_depth`` (up to ``max_size``), and retires surplus
    actors the moment they go idle with an empty backlog. Driver-side,
    single-threaded like the base pool.

    Every scale decision passes the ``data.pool.before_scale``
    failpoint (a raise-armed site skips that decision — the pool keeps
    working at its current size) and records the pool-size/queue-depth
    gauges through the goodput recorder so the federated scrape sees
    the pool breathe."""

    def __init__(self, make_actor, min_size: int = 1, max_size: int = 4,
                 *, scale_up_queue_depth: int = 2, name: str = "pool"):
        self._make_actor = make_actor
        self.min_size = max(1, int(min_size))
        self.max_size = max(self.min_size, int(max_size))
        self._scale_up_queue_depth = max(1, int(scale_up_queue_depth))
        self.name = name
        self.size = 0
        # (direction, size_after) per scale decision, in order — the
        # observability surface tests and the dataflow bench read.
        self.scale_events: list = []
        super().__init__([])
        for _ in range(self.min_size):
            self._grow(initial=True)

    def _record_gauges(self) -> None:
        try:
            from ray_tpu.util import goodput

            goodput.record_pool_size(self.name, self.size,
                                     len(self._backlog))
        except Exception:
            pass

    def _grow(self, initial: bool = False) -> bool:
        if not initial:
            from ray_tpu.util import failpoints

            try:
                failpoints.hit("data.pool.before_scale")
            except failpoints.FailpointError:
                return False  # chaos vetoed this decision; stay as-is
        try:
            actor = self._make_actor()
        except Exception:
            return False  # no capacity for another actor: stay as-is
        self.size += 1
        if not initial:
            self.scale_events.append(("up", self.size))
        self._record_gauges()
        # ActorPool._recycle drains one backlog entry onto the new actor.
        super()._recycle(actor)
        return True

    def submit(self, fn, value):
        if not self._idle and self.size < self.max_size and \
                len(self._backlog) + 1 >= self._scale_up_queue_depth:
            self._grow()
        super().submit(fn, value)

    def _recycle(self, actor):
        if not self._backlog and self.size > self.min_size:
            # Idle with nothing queued: retire the surplus actor now
            # (scale-down-on-idle; its finished results live in the
            # object store, not in the actor).
            from ray_tpu.util import failpoints

            try:
                failpoints.hit("data.pool.before_scale")
            except failpoints.FailpointError:
                super()._recycle(actor)
                return
            self.size -= 1
            self.scale_events.append(("down", self.size))
            self._record_gauges()
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
            return
        super()._recycle(actor)

    @property
    def peak_size(self) -> int:
        return max([self.min_size]
                   + [s for _d, s in self.scale_events])

    def shutdown(self) -> None:
        """Kill the remaining (idle) actors and zero the gauges. Call
        only after every result was consumed."""
        for actor in list(self._idle):
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        self._idle.clear()
        self.size = 0
        self._record_gauges()
