"""JAX/XLA device telemetry: HBM gauges, compile counters, trace capture.

The host-side observability plane (node reporter /proc stats, task
events) sees *that* a task ran; this module sees what it did to the
device. Three surfaces:

* ``snapshot()`` — per-device view from ``jax.local_devices()`` +
  ``device.memory_stats()`` (HBM bytes in use / peak / limit on TPU;
  CPU devices report no memory stats) plus process-wide JAX compile
  counters, as a plain dict that rides the worker-events RPC batch.
* compile counters — ``jax.monitoring`` listeners counting backend
  compiles / compile seconds and (persistent) compilation-cache
  hits/misses, installed once per process on first snapshot.
* ``capture(duration_s)`` — a timed ``jax.profiler.trace()`` window
  returning the trace directory as ``{relpath: bytes}``, falling back
  to the pure-Python stack sampler (``util/stack_sampler``) when
  ``jax.profiler`` is unavailable or fails.

Everything degrades to a stub when jax is not loaded: this module NEVER
imports jax itself (workers fork fast precisely because jax loads
lazily; a node agent must never initialize a TPU backend and steal the
chip from its workers). ``snapshot(force=True)`` opts a process in
explicitly.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

_lock = threading.Lock()
_listeners_installed = False
_listeners_installing = False
# Per-listener success flags: a partial failure must retry ONLY the
# listener that failed — re-registering the one that succeeded would
# double-count every event (jax.monitoring has no unregister).
_event_registered = False
_duration_registered = False
_install_failures = 0
_MAX_INSTALL_FAILURES = 5  # then give up: API is genuinely absent
# Process-wide compile counters, fed by jax.monitoring listeners.
_counts = {
    "backend_compiles": 0,
    "compile_seconds": 0.0,
    "cache_hits": 0,
    "cache_misses": 0,
    "compile_requests": 0,
}

# Keys copied out of device.memory_stats() when present (TPU/GPU
# backends; CPU returns None).
_MEM_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
             "largest_alloc_size", "num_allocs")


def jax_loaded() -> bool:
    """Has something in this process already imported jax? (We piggyback
    on their import; we never trigger one.)"""
    return "jax" in sys.modules


def _install_listeners() -> None:
    """Register jax.monitoring hooks once per process. Retry-safe: the
    installed flag is only set after a successful registration, so a
    failed attempt (e.g. racing a partially-finished jax import) is
    retried on the next call instead of silently disabling counting.
    Caller guarantees ``sys.modules`` has jax (possibly mid-import —
    the submodule import below then just blocks on the import lock)."""
    global _listeners_installed, _listeners_installing
    global _event_registered, _duration_registered, _install_failures
    with _lock:
        if _listeners_installed or _listeners_installing or \
                _install_failures >= _MAX_INSTALL_FAILURES:
            return
        _listeners_installing = True
    try:
        try:
            from jax import monitoring
        except Exception:
            with _lock:
                _install_failures += 1
            return  # retried on the next ensure_listeners/snapshot

        def on_event(name: str, **kw):
            if name.endswith("/cache_hits"):
                key = "cache_hits"
            elif name.endswith("/cache_misses"):
                key = "cache_misses"
            elif name.endswith("/compile_requests_use_cache"):
                key = "compile_requests"
            else:
                return
            with _lock:
                _counts[key] += 1

        def on_duration(name: str, secs: float, **kw):
            if name.endswith("/backend_compile_duration"):
                with _lock:
                    _counts["backend_compiles"] += 1
                    _counts["compile_seconds"] += float(secs)

        ok = True
        if not _event_registered:
            try:
                monitoring.register_event_listener(on_event)
                _event_registered = True
            except Exception:
                ok = False
        if not _duration_registered:
            try:
                monitoring.register_event_duration_secs_listener(
                    on_duration)
                _duration_registered = True
            except Exception:
                ok = False
        with _lock:
            if ok:
                _listeners_installed = True
            else:
                _install_failures += 1  # bounded retries of the FAILED half
    finally:
        with _lock:
            _listeners_installing = False


def ensure_listeners() -> bool:
    """Attach the compile-counter listeners as soon as jax is importable
    in this process (idempotent, never imports jax itself). Workers call
    this from their event-flush tick, so counting starts within ~250ms
    of jax appearing — compiles issued before the attach (typically the
    first task's very first jit) are not retroactively countable."""
    if not jax_loaded():
        return False
    _install_listeners()
    return True


def compile_counts() -> Dict[str, Any]:
    with _lock:
        out = dict(_counts)
    out["compile_seconds"] = round(out["compile_seconds"], 4)
    return out


def _stub(ts: float, error: str | None = None) -> Dict[str, Any]:
    snap: Dict[str, Any] = {
        "available": False,
        "platform": None,
        "devices": [],
        "compile": compile_counts(),
        "ts": ts,
        "pid": os.getpid(),
    }
    if error:
        snap["error"] = error
    return snap


def snapshot(force: bool = False) -> Dict[str, Any]:
    """Current device view of THIS process. A stub (``available: False``)
    when jax was never imported here — pass ``force=True`` to import it
    (drivers/benchmarks that want the telemetry to pull jax in)."""
    ts = time.time()
    if not force and not jax_loaded():
        return _stub(ts)
    try:
        import jax
    except Exception as e:  # forced on a box without jax
        return _stub(ts, error=repr(e))
    _install_listeners()
    try:
        devices = jax.local_devices()
    except Exception as e:  # backend init failed (no TPU, bad plugin...)
        return _stub(ts, error=repr(e))
    out = []
    for d in devices:
        rec: Dict[str, Any] = {
            "id": getattr(d, "id", -1),
            "platform": getattr(d, "platform", "?"),
            "device_kind": getattr(d, "device_kind", "?"),
            "process_index": getattr(d, "process_index", 0),
            "memory_stats": False,
        }
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            rec["memory_stats"] = True
            for k in _MEM_KEYS:
                if k in stats:
                    rec[k] = stats[k]
        out.append(rec)
    return {
        "available": True,
        "platform": out[0]["platform"] if out else None,
        "devices": out,
        "compile": compile_counts(),
        "ts": ts,
        "pid": os.getpid(),
    }


# -- remote profiler capture ---------------------------------------------


def _read_dir(root: str) -> Dict[str, bytes]:
    files: Dict[str, bytes] = {}
    for dirpath, _dirs, names in os.walk(root):
        for name in names:
            path = os.path.join(dirpath, name)
            rel = os.path.relpath(path, root)
            try:
                with open(path, "rb") as f:
                    files[rel] = f.read()
            except OSError:
                continue
    return files


def capture_to_dir(out_dir: str, duration_s: float = 1.0,
                   interval_s: float = 0.01, force_stack: bool = False,
                   worker_id: Optional[str] = None) -> Dict[str, Any]:
    """Profile THIS process for ``duration_s``, writing the trace files
    straight into ``out_dir`` (no bytes held in memory — a TPU trace
    window routinely reaches hundreds of MB, and on a node the agent
    and its workers share the filesystem, so the capture RPC only needs
    to carry the manifest).

    With jax loaded (and ``jax.profiler`` working) this opens a
    ``jax.profiler.trace(out_dir)`` window — XLA host+device activity
    lands there as a TensorBoard-compatible trace directory. Otherwise
    (or on any profiler failure) it degrades to the PR-1 stack sampler.
    Returns ``{kind, files: {relpath: size}, ...}``.
    """
    duration_s = max(0.05, float(duration_s))
    os.makedirs(out_dir, exist_ok=True)
    kind = None
    if not force_stack and jax_loaded():
        try:
            import jax.profiler

            with jax.profiler.trace(out_dir):
                time.sleep(duration_s)
            if any(files for _, _, files in os.walk(out_dir)):
                kind = "jax_profiler"
        except Exception:
            kind = None  # fall through to the stack sampler
    if kind is None:
        from ray_tpu.util import stack_sampler

        prof = stack_sampler.sample(duration_s, interval_s)
        prof["worker_id"] = worker_id
        for name, blob in (
            ("stack_trace.json",
             json.dumps(stack_sampler.chrome_trace(prof)).encode()),
            ("stack_collapsed.txt", stack_sampler.collapsed(prof).encode()),
            ("stack_report.txt", stack_sampler.text_report(prof).encode()),
        ):
            with open(os.path.join(out_dir, name), "wb") as f:
                f.write(blob)
        kind = "stack_sampler"
    files: Dict[str, int] = {}
    for dirpath, _dirs, names in os.walk(out_dir):
        for name in names:
            path = os.path.join(dirpath, name)
            try:
                files[os.path.relpath(path, out_dir)] = \
                    os.path.getsize(path)
            except OSError:
                continue
    return {
        "kind": kind,
        "worker_id": worker_id,
        "pid": os.getpid(),
        "duration_s": duration_s,
        "dir": out_dir,
        "files": files,
    }


def capture(duration_s: float = 1.0, interval_s: float = 0.01,
            force_stack: bool = False,
            worker_id: Optional[str] = None) -> Dict[str, Any]:
    """In-memory variant of :func:`capture_to_dir` — same result shape
    but ``files`` maps relpath to BYTES (callers that can't share a
    filesystem with this process). Prefer capture_to_dir for anything
    that may run on a TPU: traces there don't fit comfortably in one
    in-memory dict."""
    import shutil
    import tempfile

    root = tempfile.mkdtemp(prefix="ray_tpu_tprof_")
    try:
        res = capture_to_dir(root, duration_s, interval_s, force_stack,
                             worker_id)
        res["files"] = _read_dir(root)
        del res["dir"]
        return res
    finally:
        shutil.rmtree(root, ignore_errors=True)


def resolve_capture_path(out_dir: str, name: str) -> Optional[str]:
    """Resolve a capture-relative file name under ``out_dir`` (creating
    parent dirs), or None if the name would escape it. The ONE
    sanitization point for every consumer that writes remote-supplied
    capture names to local disk (write_capture, the client's chunked
    download)."""
    rel = os.path.normpath(name)
    if rel.startswith("..") or os.path.isabs(rel):
        return None
    path = os.path.join(out_dir, rel)
    os.makedirs(os.path.dirname(path) or out_dir, exist_ok=True)
    return path


def write_capture(result: Dict[str, Any], out_dir: str) -> list[str]:
    """Materialize a capture's files under ``out_dir``; returns the
    written paths (capture consumers: CLI, state API)."""
    written = []
    for rel, blob in (result.get("files") or {}).items():
        path = resolve_capture_path(out_dir, rel)
        if path is None:
            continue  # never let a remote path escape out_dir
        with open(path, "wb") as f:
            f.write(blob)
        written.append(path)
    return written
