"""Training goodput plane: input-pipeline + per-step train telemetry.

Every producer on the training path — dataset stage execution,
``iter_batches``/``iter_device_batches`` consumer loops, the per-worker
``session.report`` step accounting, and the trainer's downtime ledger —
records its observation here. Recording is two-sided by design (the
PR-8 serve shape):

* the observation lands in THIS process's metric registry immediately
  (the local backend runs train workers as in-process threads, so the
  process registry is exactly what ``/metrics`` scrapes there);
* the same observation is appended to a bounded ship buffer that the
  worker's event flusher drains over the existing worker-events plane
  (``rpc_worker_events`` grew a ``train`` batch), so on the cluster
  backend — where train workers are worker processes whose registries
  nothing scrapes — the node agent replays it into the agent registry
  that federates on ``/metrics/cluster``.

Gauge children created by a worker's events (the per-rank straggler
gauge) are tracked per worker by the agent and retracted when the
worker dies, same lifecycle as the serve replica gauges.

Also here: the readers behind ``state.data_stats()`` /
``state.train_stats()``, ``ray-tpu data|train stats``, the dashboard
panes and ``scripts/input_bench.py`` — one parser (shared with the
serve plane), so the CLI, the dashboard and the bench cross-check can
never disagree about what the exposition says.

The derived **stall fraction** is the plane's headline number: the
fraction of a consumer loop's wall time spent starved for data
(``wait / (wait + user)`` over the iterator phase histograms). Check it
before blaming kernels — at pod scale the input pipeline, not the MXU,
is where step time silently goes.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _metrics

# Phases a training step decomposes into (the train histogram's phase
# tag values). ``step`` is the residual compute time between reports
# after data waits / checkpoint traffic are subtracted out.
STEP_PHASES = ("data_wait", "step", "report", "checkpoint_save",
               "checkpoint_restore")
# Phases of one *instrumented* step's anatomy decomposition (the
# round-19 step anatomy plane): data_wait = input starvation, host =
# dispatch until device launch, compute = synced device wall, sync =
# barrier skew (this rank's wait for the slowest rank — the session
# computes it as the residual, so the four phases partition the
# instrumented step wall exactly).
ANATOMY_PHASES = ("data_wait", "host", "compute", "sync")
# The slowest rank's excess classified by the phase that carries it.
ANATOMY_CAUSES = {"data_wait": "input-bound", "host": "compute-bound",
                  "compute": "compute-bound", "sync": "sync-bound"}
# Phases of one consumer-loop batch (the data iterator histogram's
# phase tag values): wait = consumer starved for the next batch,
# user = consumer's own time between batches, transfer = host->device
# dispatch inside ``iter_device_batches``.
ITER_PHASES = ("wait", "user", "transfer")

_LOCAL_NODE = "local"
# Ship buffer drained by workerproc's event flusher; bounded so a
# process nothing drains (the local-backend driver) stays flat.
_buf: "collections.deque" = collections.deque(maxlen=8192)
_buf_lock = threading.Lock()
_buf_dropped = 0


def _emit(ev: dict) -> None:
    """Observe locally and queue for the agent (see module docstring)."""
    global _buf_dropped
    try:
        apply_events([ev], node_id=_LOCAL_NODE)
    except Exception:
        pass
    with _buf_lock:
        if len(_buf) == _buf.maxlen:
            _buf_dropped += 1  # deque discards the oldest silently
        _buf.append(ev)


def drain_events() -> List[dict]:
    """Pop queued observations (the worker event flusher's hook). A
    preceding overflow is reported as a leading drop event so the
    agent's registry counts exactly what this process lost."""
    global _buf_dropped
    with _buf_lock:
        out = list(_buf)
        _buf.clear()
        if _buf_dropped:
            out.insert(0, {"k": "drop", "n": _buf_dropped})
            _buf_dropped = 0
    return out


def requeue_events(events: List[dict]) -> None:
    """Put drained observations back at the FRONT of the ship buffer
    (the worker flusher calls this when the agent upload fails). The
    goodput plane promises exact counts — a chaos-severed channel must
    not silently lose them; overflow beyond capacity counts as drops,
    oldest first."""
    global _buf_dropped
    if not events:
        return
    with _buf_lock:
        space = _buf.maxlen - len(_buf)
        if space < len(events):
            _buf_dropped += len(events) - space
            events = events[len(events) - space:]
        _buf.extendleft(reversed(events))


# -- recording (producers call these) --------------------------------------


def record_stage(stage: str, wall_s: float,
                 blocks: Optional[List[Tuple[float, int, int]]] = None
                 ) -> None:
    """One executed dataset stage: total wall seconds plus per-block
    (duration_s, rows, bytes) samples."""
    ev: dict = {"k": "stage", "s": stage, "w": float(wall_s)}
    if blocks:
        # duration None = unknown (actor-pool stages): sizes still
        # observe; no fabricated 0.0s duration samples.
        ev["b"] = [(None if d is None else float(d), int(r), int(n))
                   for d, r, n in blocks]
    _emit(ev)


def record_block_split(stage: str, n_splits: int) -> None:
    """A stage task split one oversized output block into extra
    store-friendly blocks (``n_splits`` = extra blocks beyond the
    first). Runs inside worker tasks, so the observation rides the
    worker-events replay on the cluster backend."""
    if n_splits > 0:
        _emit({"k": "split", "s": str(stage), "n": int(n_splits)})


def record_pool_size(pool: str, size: int, queue_depth: int) -> None:
    """An autoscaling dataset actor pool changed size (or reports its
    terminal size): the pool-size / queue-depth gauges, sampled at
    scale decisions."""
    _emit({"k": "pool", "s": str(pool), "n": int(size),
           "q": int(queue_depth)})


def record_iter_batch(wait_s: Optional[float] = None,
                      user_s: Optional[float] = None,
                      transfer_s: Optional[float] = None,
                      occupancy: Optional[int] = None) -> None:
    """One consumer-loop batch: starvation wait vs consumer time (plus
    host->device dispatch seconds and the prefetch-buffer occupancy the
    consumer observed). Only the phases actually measured are emitted —
    exact per-phase counts are the plane's contract, so an
    unmeasured phase must not observe a zero."""
    p: Dict[str, float] = {}
    if wait_s is not None:
        p["wait"] = max(0.0, float(wait_s))
    if user_s is not None:
        p["user"] = max(0.0, float(user_s))
    if transfer_s is not None:
        p["transfer"] = max(0.0, float(transfer_s))
    ev: dict = {"k": "it", "p": p}
    if occupancy is not None:
        ev["occ"] = int(occupancy)
    if p or occupancy is not None:
        _emit(ev)


def record_step(trial: str, rank: int, phases: Dict[str, float]) -> None:
    """One reported training step's phase breakdown for one rank. Also
    feeds the per-rank straggler gauge (retracted with the worker)."""
    phases = {p: max(0.0, float(s)) for p, s in phases.items()
              if p in STEP_PHASES}
    _emit({"k": "step", "t": str(trial), "r": int(rank), "p": phases})


def record_anatomy(trial: str, rank: int, phases: Dict[str, float],
                   mfu: Optional[float] = None) -> None:
    """One instrumented step's anatomy decomposition for one rank
    (``data_wait`` / ``host`` / ``compute`` / ``sync`` — the session
    computes ``sync`` as the residual, so the phases partition the
    instrumented step wall exactly). ``mfu`` is the cost-model MFU
    percent when a step cost is attached. Per-rank gauges, retracted
    on worker death and session stop."""
    phases = {p: max(0.0, float(s)) for p, s in phases.items()
              if p in ANATOMY_PHASES}
    ev: dict = {"k": "anat", "t": str(trial), "r": int(rank),
                "p": phases}
    if mfu is not None:
        ev["m"] = float(mfu)
    _emit(ev)


def record_downtime(trial: str, cause: str, seconds: float) -> None:
    """Non-productive trial wall time attributed to a cause (the
    trainer's downtime ledger: restart/drain/preemption)."""
    _emit({"k": "down", "t": str(trial), "c": str(cause),
           "s": max(0.0, float(seconds))})


def downtime_cause(exc: BaseException) -> str:
    """Classify a trial-interrupting failure into a downtime-ledger
    cause using the PR-2 cause plumbing: the HEAD-generated drain
    formats ("node <id> died: drained: <reason>" / "node <id>
    draining: ...") and the trainer's proactive-preemption restart map
    to planned causes; everything else is a plain failure."""
    import re

    s = str(exc)
    m = re.search(r"died: drained: ([\w.-]+)", s)
    if m:
        return f"drain:{m.group(1)}"
    if re.search(r"node \S+ draining:", s):
        return "drain"
    if "Preempted" in type(exc).__name__:
        return "preemption"
    return "failure"


def straggler_attribution(rank_phases: Dict[str, Dict[str, float]],
                          min_excess_frac: float = 0.05
                          ) -> Optional[dict]:
    """Head-side straggler attributor: name the slowest rank of a gang
    and classify its excess into input-bound / compute-bound /
    sync-bound.

    ``rank_phases`` maps rank -> anatomy phase seconds. The slowest
    rank is the one with the most *own work* (everything but ``sync``
    — in lockstep every rank's wall is identical, the barrier wait is
    what differs, so ranking by wall would name nobody). Its excess
    over the median of the other ranks is attributed to the phase with
    the largest delta vs that median. Below ``min_excess_frac`` of the
    baseline the gang is ``balanced`` — no rank gets accused of noise.

    One implementation shared by ``train_stats``, ``ray-tpu top`` and
    the anatomy bench, so they can never disagree about who the
    straggler is."""
    if not rank_phases or len(rank_phases) < 2:
        return None

    def own(p: Dict[str, float]) -> float:
        return sum(v for k, v in p.items() if k != "sync")

    totals = {r: own(p) for r, p in rank_phases.items()}
    slowest = max(totals, key=lambda r: totals[r])
    rest = sorted(t for r, t in totals.items() if r != slowest)
    baseline = rest[len(rest) // 2]
    excess = totals[slowest] - baseline
    out = {"rank": slowest, "own_s": round(totals[slowest], 6),
           "baseline_s": round(baseline, 6),
           "excess_s": round(max(0.0, excess), 6)}
    if baseline > 0 and excess < min_excess_frac * baseline:
        out["cause"] = "balanced"
        return out
    deltas = {}
    for phase in ANATOMY_PHASES:
        others = sorted(rank_phases[r].get(phase, 0.0)
                        for r in rank_phases if r != slowest)
        med = others[len(others) // 2] if others else 0.0
        deltas[phase] = rank_phases[slowest].get(phase, 0.0) - med
    worst_phase = max(deltas, key=lambda p: deltas[p])
    out["phase"] = worst_phase
    out["cause"] = ANATOMY_CAUSES[worst_phase]
    return out


def attribution_ok(goodput: dict) -> Tuple[bool, bool]:
    """The ledger-contract check every preemption harness shares:
    ``(planned, sums)`` — *planned* is True when every ``by_cause`` key
    is a planned cause (``preemption`` / ``reschedule`` /
    ``drain:<reason>``/``drain``), *sums* when the causes sum exactly
    (1e-6) to ``downtime_s``. One implementation so the chaos soak and
    the gang bench can never disagree about what "fully attributed"
    means."""
    by_cause = goodput.get("by_cause") or {}
    planned = all(
        c in ("preemption", "reschedule") or c.startswith("drain")
        for c in by_cause)
    sums = abs(sum(by_cause.values())
               - (goodput.get("downtime_s") or 0.0)) < 1e-6
    return planned, sums


class GoodputLedger:
    """Attributes every non-productive second of a trial's wall time to
    a cause (the PR-2/PR-5 plumbing: drain reason, preemption, plain
    failure). Downtime opens when an attempt dies and closes at the
    NEXT attempt's first report — the moment training is provably
    making progress again — so restart cost (group placement, jax
    re-init, checkpoint restore wait) is all accounted, never
    unattributed wall time. Shared by the trainer (``Result.goodput``)
    and Tune trials (``Trial.goodput()``)."""

    def __init__(self, trial: str = "train"):
        self.trial = trial
        self.t0 = time.monotonic()
        self.by_cause: Dict[str, float] = {}
        self.restarts = 0
        self._down_since: Optional[float] = None
        self._down_cause: Optional[str] = None
        self.rank_step_s: Dict[int, float] = {}

    def mark_down(self, cause: str) -> None:
        if self._down_since is None:
            self._down_since = time.monotonic()
            self._down_cause = cause

    def _close_interval(self, restarted: bool) -> None:
        if self._down_since is None:
            return
        dt = time.monotonic() - self._down_since
        cause = self._down_cause or "failure"
        self.by_cause[cause] = self.by_cause.get(cause, 0.0) + dt
        # A restart only counts when PROGRESS closed the interval — a
        # trial that ends on a terminal failure never restarted.
        if restarted:
            self.restarts += 1
        self._down_since = None
        self._down_cause = None
        try:
            record_downtime(self.trial, cause, dt)
        except Exception:
            pass

    def mark_progress(self) -> None:
        """Training is provably making progress again (a report was
        accepted): close an open downtime interval as a restart."""
        self._close_interval(restarted=True)

    def observe_report(self, msg: dict) -> None:
        self.mark_progress()
        phases = msg.get("phases") or {}
        if "step" in phases:
            self.rank_step_s[msg.get("rank", 0)] = phases["step"]

    def _view(self, extra_open: float) -> dict:
        wall = time.monotonic() - self.t0
        by_cause = {c: round(s, 3) for c, s in self.by_cause.items()}
        if extra_open > 0:
            cause = self._down_cause or "failure"
            by_cause[cause] = round(
                by_cause.get(cause, 0.0) + extra_open, 3)
        down = round(sum(by_cause.values()), 3)
        out: dict = {
            "wall_s": round(wall, 3),
            "downtime_s": down,
            "by_cause": by_cause,
            "restarts": self.restarts,
            "goodput_pct": round(
                100.0 * max(0.0, wall - down) / wall, 2)
            if wall > 0 else None,
        }
        if self.rank_step_s:
            out["rank_step_s"] = {
                r: round(s, 4)
                for r, s in sorted(self.rank_step_s.items())}
            fastest = min(self.rank_step_s.values())
            if fastest > 0:
                out["rank_skew"] = round(
                    max(self.rank_step_s.values()) / fastest, 3)
        return out

    def snapshot(self) -> dict:
        """Non-mutating read: an OPEN downtime interval is included in
        the view (up to now) but stays open, so a dashboard poll can
        never swallow downtime that the eventual recovery should
        attribute."""
        open_s = (time.monotonic() - self._down_since) \
            if self._down_since is not None else 0.0
        return self._view(open_s)

    def summary(self) -> dict:
        """Terminal rollup: the trial is over, so an interval still
        open is closed (attributed, not counted as a restart)."""
        self._close_interval(restarted=False)
        return self._view(0.0)


# -- replay (the node agent and the local registry) ------------------------


def apply_events(events: List[dict], node_id: str,
                 worker: Optional[str] = None) -> List[Tuple]:
    """Replay shipped observations into THIS process's registry (the
    node agent calls this with its node_id + the reporting worker's
    id). Returns the gauge keys the batch touched so the agent can
    retract them when the worker dies."""
    worker = worker or str(os.getpid())
    gauge_keys: List[Tuple] = []
    for ev in events or []:
        try:
            kind = ev.get("k")
            if kind == "stage":
                stage = ev.get("s", "")
                _metrics.DATA_STAGE_SECONDS.observe(
                    float(ev.get("w", 0.0)),
                    tags={"node_id": node_id, "stage": stage})
                for dur, rows, nbytes in ev.get("b") or ():
                    tags = {"node_id": node_id, "stage": stage}
                    if dur is not None:
                        _metrics.DATA_BLOCK_SECONDS.observe(float(dur),
                                                            tags=tags)
                    _metrics.DATA_BLOCK_ROWS.observe(float(rows),
                                                     tags=tags)
                    _metrics.DATA_BLOCK_BYTES.observe(float(nbytes),
                                                      tags=tags)
            elif kind == "it":
                for phase, sec in (ev.get("p") or {}).items():
                    if phase in ITER_PHASES:
                        _metrics.DATA_ITER_SECONDS.observe(
                            float(sec), tags={"node_id": node_id,
                                              "phase": phase})
                if ev.get("occ") is not None:
                    _metrics.DATA_PREFETCH_OCCUPANCY.observe(
                        float(ev["occ"]), tags={"node_id": node_id})
            elif kind == "step":
                trial = ev.get("t", "train")
                rank = str(ev.get("r", 0))
                phases = ev.get("p") or {}
                for phase, sec in phases.items():
                    _metrics.TRAIN_STEP_PHASE_SECONDS.observe(
                        float(sec), tags={"node_id": node_id,
                                          "trial": trial,
                                          "phase": phase})
                _metrics.TRAIN_REPORTS_TOTAL.inc(
                    tags={"node_id": node_id, "trial": trial})
                if "step" in phases:
                    _metrics.TRAIN_RANK_STEP_SECONDS.set(
                        float(phases["step"]),
                        tags={"node_id": node_id, "trial": trial,
                              "rank": rank})
                    gauge_keys.append(("rank", trial, rank))
            elif kind == "anat":
                trial = ev.get("t", "train")
                rank = str(ev.get("r", 0))
                for phase, sec in (ev.get("p") or {}).items():
                    if phase in ANATOMY_PHASES:
                        _metrics.TRAIN_STEP_ANATOMY_SECONDS.set(
                            float(sec),
                            tags={"node_id": node_id, "trial": trial,
                                  "phase": phase, "rank": rank})
                if ev.get("m") is not None:
                    _metrics.TRAIN_MFU_PERCENT.set(
                        float(ev["m"]),
                        tags={"node_id": node_id, "trial": trial,
                              "rank": rank})
                gauge_keys.append(("anat", trial, rank))
            elif kind == "down":
                _metrics.TRAIN_DOWNTIME_SECONDS.inc(
                    float(ev.get("s", 0.0)),
                    tags={"node_id": node_id,
                          "trial": ev.get("t", "train"),
                          "cause": ev.get("c", "failure")})
            elif kind == "split":
                _metrics.DATA_BLOCK_SPLITS.inc(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "stage": ev.get("s", "")})
            elif kind == "pool":
                pool = ev.get("s", "")
                _metrics.DATA_POOL_SIZE.set(
                    float(ev.get("n", 0)),
                    tags={"node_id": node_id, "pool": pool})
                _metrics.DATA_POOL_QUEUE_DEPTH.set(
                    float(ev.get("q", 0)),
                    tags={"node_id": node_id, "pool": pool})
                gauge_keys.append(("pool", pool))
            elif kind == "drop":
                _metrics.TRAIN_EVENTS_DROPPED.inc(
                    float(ev.get("n", 0)), tags={"node_id": node_id})
        except Exception:
            continue  # one bad event must not drop the batch
    return gauge_keys


def retract_gauges(keys, node_id: str) -> None:
    """Drop the gauge children a dead worker's events created (the
    federated scrape must not keep reporting a dead rank's step
    time)."""
    for key in keys or ():
        try:
            if key[0] == "rank":
                _metrics.TRAIN_RANK_STEP_SECONDS.remove(tags={
                    "node_id": node_id, "trial": key[1], "rank": key[2]})
            elif key[0] == "anat":
                for phase in ANATOMY_PHASES:
                    try:
                        _metrics.TRAIN_STEP_ANATOMY_SECONDS.remove(
                            tags={"node_id": node_id, "trial": key[1],
                                  "phase": phase, "rank": key[2]})
                    except Exception:
                        pass
                _metrics.TRAIN_MFU_PERCENT.remove(tags={
                    "node_id": node_id, "trial": key[1], "rank": key[2]})
            elif key[0] == "trial":
                # Session-stop sweep: drop EVERY per-rank child of the
                # trial from this process's registry (the local backend
                # runs workers as threads — nothing dies to trigger the
                # agent's worker-death retraction).
                for fam in (_metrics.TRAIN_RANK_STEP_SECONDS,
                            _metrics.TRAIN_MFU_PERCENT,
                            _metrics.TRAIN_STEP_ANATOMY_SECONDS):
                    for ld in fam.series():
                        if ld.get("trial") == key[1]:
                            try:
                                fam.remove(tags=ld)
                            except Exception:
                                pass
            elif key[0] == "pool":
                _metrics.DATA_POOL_SIZE.remove(tags={
                    "node_id": node_id, "pool": key[1]})
                _metrics.DATA_POOL_QUEUE_DEPTH.remove(tags={
                    "node_id": node_id, "pool": key[1]})
        except Exception:
            pass


def retract_trial(trial: str, node_id: str = _LOCAL_NODE) -> None:
    """Session stop: retract the trial's per-rank gauge series (step
    time, MFU, anatomy phases) from this process's registry. The
    trainer calls this when a trial finishes; on the cluster backend
    the agent's worker-death sweep covers its copies."""
    retract_gauges([("trial", str(trial))], node_id)


# -- reading the plane back (state.train_stats / data_stats / bench) -------
#
# The parse helpers are shared with the serve plane (ONE parser for
# every reader of the exposition format); the scrape body here merges
# the backend's federated text with THIS process's registry, because a
# cluster driver's own emissions (trainer downtime ledger, driver-side
# dataset stages) never ride the worker-events plane. merge_prometheus
# dedups by series identity, so in-process clusters — where the driver
# and the agents share one registry — don't double count.


def _parse_helpers():
    from ray_tpu.serve import _observability as serve_obs

    return serve_obs


def scrape_text() -> str:
    """Cluster-federated exposition merged with this process's own
    registry (see above)."""
    from ray_tpu._private import worker as _worker

    local = _metrics.prometheus_text()
    try:
        backend = _worker.backend()
    except Exception:
        backend = None
    if backend is not None and hasattr(backend, "cluster_metrics_text"):
        try:
            return _metrics.merge_prometheus(
                [backend.cluster_metrics_text(), local])
        except Exception:
            pass
    return local


def _dist_summary(obs, dist: Optional[dict]) -> Optional[dict]:
    if not dist:
        return None
    out = {"count": int(dist["count"]),
           "sum_s": round(dist["sum"], 6),
           "mean_ms": round(dist["sum"] / dist["count"] * 1e3, 3)}
    p50 = obs.quantile_from_buckets(dist, 0.50)
    p99 = obs.quantile_from_buckets(dist, 0.99)
    out["p50_ms"] = round(p50 * 1e3, 3) if p50 is not None else None
    out["p99_ms"] = round(p99 * 1e3, 3) if p99 is not None else None
    return out


def stall_fraction_from(parsed: dict) -> Optional[float]:
    """Metrics-derived stall fraction: wait seconds / (wait + user)
    summed over every node's iterator histograms. None until a
    consumer loop has recorded at least one batch."""
    obs = _parse_helpers()
    wait = obs.histogram_dist(parsed, "ray_tpu_data_iter_seconds",
                              phase="wait")
    user = obs.histogram_dist(parsed, "ray_tpu_data_iter_seconds",
                              phase="user")
    if not wait or not user:
        return None
    denom = wait["sum"] + user["sum"]
    if denom <= 0:
        return None
    return wait["sum"] / denom


def data_stats(parsed: Optional[dict] = None) -> dict:
    """Input-pipeline rollup from the metrics plane: per-stage wall /
    per-block distributions, consumer-loop wait/user/transfer, prefetch
    occupancy, and the derived stall fraction."""
    obs = _parse_helpers()
    if parsed is None:
        parsed = obs.parse_prometheus(scrape_text())
    stages: dict = {}
    stage_names = set(obs.sum_counter(
        parsed, "ray_tpu_data_stage_seconds_count", "stage"))
    for name in sorted(n for n in stage_names if n):
        entry: dict = {}
        wall = obs.histogram_dist(parsed, "ray_tpu_data_stage_seconds",
                                  stage=name)
        if wall:
            entry["executions"] = int(wall["count"])
            entry["wall_ms"] = round(wall["sum"] * 1e3, 3)
        blk = obs.histogram_dist(parsed, "ray_tpu_data_block_seconds",
                                 stage=name)
        if blk:
            entry["blocks"] = int(blk["count"])
            entry["block_seconds"] = _dist_summary(obs, blk)
        rows = obs.histogram_dist(parsed, "ray_tpu_data_block_rows",
                                  stage=name)
        if rows:
            entry["rows_total"] = int(rows["sum"])
        nbytes = obs.histogram_dist(parsed, "ray_tpu_data_block_bytes",
                                    stage=name)
        if nbytes:
            entry["bytes_total"] = int(nbytes["sum"])
            if wall and wall["sum"] > 0:
                entry["bytes_per_s"] = round(nbytes["sum"] / wall["sum"])
        stages[name] = entry
    out: dict = {"stages": stages}
    iterator: dict = {}
    for phase in ITER_PHASES:
        d = obs.histogram_dist(parsed, "ray_tpu_data_iter_seconds",
                               phase=phase)
        if d:
            iterator[phase] = _dist_summary(obs, d)
    occ = obs.histogram_dist(parsed, "ray_tpu_data_prefetch_occupancy")
    if occ:
        iterator["occupancy"] = {
            "samples": int(occ["count"]),
            "mean": round(occ["sum"] / occ["count"], 3),
        }
    if iterator:
        out["iterator"] = iterator
    sf = stall_fraction_from(parsed)
    if sf is not None:
        out["stall_fraction"] = round(sf, 4)
    return out


def train_stats(parsed: Optional[dict] = None) -> dict:
    """Per-trial training goodput rollup: reports, per-phase step
    histograms, per-rank step time (straggler skew), and the downtime
    ledger with its attribution."""
    obs = _parse_helpers()
    if parsed is None:
        parsed = obs.parse_prometheus(scrape_text())
    trials: dict = {}
    names = set(obs.sum_counter(parsed, "ray_tpu_train_reports_total",
                                "trial"))
    names |= set(obs.sum_counter(
        parsed, "ray_tpu_train_downtime_seconds_total", "trial"))
    # Anatomy-only producers (the LLM engine's step loop reports no
    # session metrics) still get a per-trial entry.
    names |= {dict(lb).get("trial", "") for lb in
              (parsed.get("ray_tpu_step_phase_seconds") or {})}
    for trial in sorted(n for n in names if n):
        entry: dict = {}
        reports = obs.sum_counter(parsed, "ray_tpu_train_reports_total",
                                  "trial", trial=trial).get(trial)
        if reports:
            entry["reports"] = int(reports)
        phases: dict = {}
        productive_s = 0.0
        for phase in STEP_PHASES:
            d = obs.histogram_dist(
                parsed, "ray_tpu_train_step_phase_seconds",
                trial=trial, phase=phase)
            if d:
                phases[phase] = _dist_summary(obs, d)
                productive_s += d["sum"]
        if phases:
            entry["phases"] = phases
        ranks = {}
        for labels, val in (parsed.get(
                "ray_tpu_train_rank_step_seconds") or {}).items():
            ld = dict(labels)
            if ld.get("trial") == trial:
                ranks[ld.get("rank", "?")] = round(val, 6)
        if ranks:
            entry["rank_step_s"] = dict(sorted(ranks.items()))
            fastest = min(ranks.values())
            if fastest > 0:
                entry["rank_skew"] = round(max(ranks.values()) / fastest,
                                           3)
        anat_ranks: Dict[str, Dict[str, float]] = {}
        for labels, val in (parsed.get(
                "ray_tpu_step_phase_seconds") or {}).items():
            ld = dict(labels)
            if ld.get("trial") == trial:
                anat_ranks.setdefault(ld.get("rank", "?"), {})[
                    ld.get("phase", "?")] = round(val, 6)
        mfu: Dict[str, float] = {}
        for labels, val in (parsed.get(
                "ray_tpu_mfu_percent") or {}).items():
            ld = dict(labels)
            if ld.get("trial") == trial:
                mfu[ld.get("rank", "?")] = round(val, 3)
        if anat_ranks or mfu:
            anatomy: dict = {}
            if anat_ranks:
                anatomy["ranks"] = {
                    r: dict(sorted(anat_ranks[r].items()))
                    for r in sorted(anat_ranks)}
                verdict = straggler_attribution(anat_ranks)
                if verdict:
                    anatomy["straggler"] = verdict
            if mfu:
                anatomy["mfu_pct"] = dict(sorted(mfu.items()))
            entry["anatomy"] = anatomy
        downtime = obs.sum_counter(
            parsed, "ray_tpu_train_downtime_seconds_total", "cause",
            trial=trial)
        if downtime:
            entry["downtime_s"] = {
                c: round(v, 3) for c, v in downtime.items()}
        down_s = sum(downtime.values()) if downtime else 0.0
        if productive_s + down_s > 0:
            entry["goodput_pct"] = round(
                100.0 * productive_s / (productive_s + down_s), 2)
        trials[trial] = entry
    return {"trials": trials}
