"""In-mesh XLA collectives: the TPU tensor plane.

This is the TPU-native replacement for the reference's NCCL backend
(``collective_group/nccl_collective_group.py``): dense-tensor collectives
compile into the jitted program and ride ICI, instead of being framework
calls that move buffers between processes (SURVEY.md §5.8).

Two surfaces:

1. **Inside jit / shard_map** — thin aliases over ``jax.lax`` so library
   code can write ``collective.xla.allreduce(x, axis="dp")`` and stay
   backend-agnostic: the op lowers to an XLA collective on the mesh axis.

2. **`DeviceGroup`** — eager helper for code that holds per-device arrays
   OUTSIDE a jitted region: builds a 1D mesh over the chosen devices and
   runs one compiled collective over it. Useful for tests, optimizer-state
   surgery, and host-driven rendezvous steps.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from ray_tpu._compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# -- surface 1: inside jit/shard_map --------------------------------------

def allreduce(x, axis: str):
    return jax.lax.psum(x, axis_name=axis)


def allreduce_mean(x, axis: str):
    return jax.lax.pmean(x, axis_name=axis)


def allgather(x, axis: str, *, concat_axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, axis=concat_axis, tiled=tiled)


def reducescatter(x, axis: str, *, scatter_axis: int = 0):
    return jax.lax.psum_scatter(
        x, axis_name=axis, scatter_dimension=scatter_axis, tiled=True
    )


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(
        x, axis_name=axis, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )


def ppermute(x, axis: str, perm: Sequence[tuple[int, int]]):
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def broadcast(x, axis: str, src: int = 0):
    """Every rank gets src's value (gather + index — XLA fuses this)."""
    return jax.lax.all_gather(x, axis_name=axis)[src]


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


# -- surface 2: eager collectives over explicit devices -------------------

class DeviceGroup:
    """A 1D mesh over explicit devices with eager compiled collectives.

    The ``world_size``/``rank`` bookkeeping of the reference's group API
    maps to mesh positions here; rendezvous is unnecessary intra-process
    because XLA sees all member devices.
    """

    AXIS = "ranks"

    def __init__(self, devices: Optional[Sequence] = None):
        self.devices = list(devices if devices is not None else jax.devices())
        self.mesh = Mesh(self.devices, (self.AXIS,))
        self.world_size = len(self.devices)
        self._compiled: dict[str, callable] = {}

    def _sharded(self, x, spec: P):
        return jax.device_put(x, NamedSharding(self.mesh, spec))

    def _run(self, name: str, fn, x, in_spec: P, out_spec: P):
        # Cache the jitted collective per op: jax.jit caches by function
        # identity, so a fresh closure per call would recompile every time.
        compiled = self._compiled.get(name)
        if compiled is None:
            compiled = self._compiled[name] = jax.jit(
                shard_map(
                    fn, mesh=self.mesh, in_specs=(in_spec,),
                    out_specs=out_spec, check_vma=False,
                )
            )
        return compiled(self._sharded(x, in_spec))

    def allreduce(self, x):
        """x: (world, ...) stacked per-rank contributions; returns the
        elementwise sum over ranks, replicated."""
        return self._run(
            "allreduce",
            lambda s: jax.lax.psum(s[0], axis_name=self.AXIS),
            x, P(self.AXIS), P(),
        )

    def allgather(self, x):
        """x: (world, ...) stacked per-rank contributions; returns the full
        stack on every rank (i.e. x, replicated)."""
        return self._run(
            "allgather",
            lambda s: jax.lax.all_gather(s, self.AXIS, axis=0, tiled=True),
            x, P(self.AXIS), P(),
        )

    def reducescatter(self, x):
        """x: (world, k*world, ...) stacked per-rank contributions; returns
        (world, k, ...) where row r is rank r's chunk of the reduced sum."""
        return self._run(
            "reducescatter",
            lambda s: jax.lax.psum_scatter(
                s[0], self.AXIS, scatter_dimension=0, tiled=True
            )[None],
            x, P(self.AXIS), P(self.AXIS),
        )

    def barrier(self):
        """Complete a trivial collective on every member device."""
        token = jnp.zeros((self.world_size,), jnp.int32)
        jax.block_until_ready(self.allreduce(token))
