"""Collective communication over groups of actors.

Reference parity: ``python/ray/util/collective/collective.py`` — the
declarative group-management API (init/create/destroy groups, ranked ops:
allreduce/barrier/reduce/broadcast/allgather/reducescatter/send/recv).

TPU-native split (SURVEY.md §5.8): the reference backs these ops with NCCL
(cupy) or Gloo (pygloo). Here the **tensor plane is XLA** — dense-array
collectives inside jitted step functions ride ICI via ``jax.lax`` ops (see
``ray_tpu.util.collective.xla``), and the group-management/rendezvous layer
(this module) runs over the control plane: a coordinator actor is the
rendezvous store (the analog of the named actor holding the NCCL unique id,
``nccl_collective_group.py``), and host-memory collectives between actors
move numpy arrays through the object plane.
"""

from ray_tpu.util.collective.collective import (
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    destroy_collective_group,
    get_collective_group_size,
    get_rank,
    init_collective_group,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.util.collective import xla

__all__ = [
    "ReduceOp",
    "init_collective_group",
    "destroy_collective_group",
    "get_rank",
    "get_collective_group_size",
    "allreduce",
    "allgather",
    "barrier",
    "broadcast",
    "reduce",
    "reducescatter",
    "send",
    "recv",
    "xla",
]
