"""Group management + host-memory collective ops.

The coordinator actor is the rendezvous + exchange store; ranks push
contributions and poll for completeness. All ranks of a group must issue
collective calls in the same order (standard collective semantics — same
contract as the reference's NCCL/Gloo groups).

Reference: ``python/ray/util/collective/collective.py:120,151,258-594``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

import ray_tpu


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda arrs: np.sum(arrs, axis=0),
    ReduceOp.PRODUCT: lambda arrs: np.prod(arrs, axis=0),
    ReduceOp.MIN: lambda arrs: np.min(arrs, axis=0),
    ReduceOp.MAX: lambda arrs: np.max(arrs, axis=0),
}


class _Coordinator:
    """Rendezvous + exchange slots for one collective group.

    A slot is complete when ``expected`` ranks contributed; it is deleted
    after ``num_fetchers`` distinct ranks fetched it.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.slots: dict = {}  # key -> {"payloads": {rank: x}, "expected": n,
        #                               "num_fetchers": n, "fetched": set()}

    def contribute(self, key, rank, payload, expected, num_fetchers):
        slot = self.slots.setdefault(
            key,
            {"payloads": {}, "expected": expected, "num_fetchers": num_fetchers,
             "fetched": set()},
        )
        slot["payloads"][rank] = payload
        return len(slot["payloads"])

    def try_fetch(self, key, rank):
        """(ready, payloads-by-rank). GC the slot once everyone fetched."""
        slot = self.slots.get(key)
        if slot is None or len(slot["payloads"]) < slot["expected"]:
            return False, None
        payloads = slot["payloads"]
        slot["fetched"].add(rank)
        if len(slot["fetched"]) >= slot["num_fetchers"]:
            del self.slots[key]
        return True, payloads

    def ready(self, key):
        slot = self.slots.get(key)
        return slot is not None and len(slot["payloads"]) >= slot["expected"]


class _GroupContext:
    def __init__(self, name, coordinator, world_size, rank):
        self.name = name
        self.coordinator = coordinator
        self.world_size = world_size
        self.rank = rank
        self.seq = 0
        # Point-to-point ops sequence independently per (src, dst) pair —
        # only pairwise ordering matters for send/recv matching.
        self.pair_seq: dict = {}

    def next_key(self, op: str) -> str:
        self.seq += 1
        return f"{op}:{self.seq}"

    def next_pair_key(self, src: int, dst: int) -> str:
        n = self.pair_seq.get((src, dst), 0) + 1
        self.pair_seq[(src, dst)] = n
        return f"sendrecv:{src}->{dst}:{n}"

    def exchange(
        self,
        op: str,
        payload,
        *,
        contribute: bool = True,
        expected: int | None = None,
        num_fetchers: int | None = None,
        fetch: bool = True,
        poll_interval: float = 0.002,
        timeout: float = 120.0,
    ) -> Optional[dict]:
        key = self.next_key(op)
        expected = self.world_size if expected is None else expected
        num_fetchers = self.world_size if num_fetchers is None else num_fetchers
        c = self.coordinator
        if contribute:
            ray_tpu.get(
                c.contribute.remote(key, self.rank, payload, expected, num_fetchers)
            )
        deadline = time.monotonic() + timeout
        if not fetch:
            # Still wait for slot completeness so the op is a sync point.
            while not ray_tpu.get(c.ready.remote(key)):
                if time.monotonic() > deadline:
                    raise TimeoutError(f"collective {key} timed out")
                time.sleep(poll_interval)
            return None
        while True:
            ok, payloads = ray_tpu.get(c.try_fetch.remote(key, self.rank))
            if ok:
                return payloads
            if time.monotonic() > deadline:
                raise TimeoutError(f"collective {key} timed out")
            time.sleep(poll_interval)


# Group contexts are per-execution-thread: each actor worker thread (one per
# max_concurrency=1 actor) holds its own rank state, mirroring the
# per-process module state of the reference.
_local = threading.local()


def _groups() -> dict:
    if not hasattr(_local, "groups"):
        _local.groups = {}
    return _local.groups


def _ctx(group_name: str) -> _GroupContext:
    try:
        return _groups()[group_name]
    except KeyError:
        raise ValueError(
            f"collective group {group_name!r} is not initialized in this "
            f"worker; call init_collective_group first"
        ) from None


def _coordinator_name(group_name: str) -> str:
    return f"ray_tpu.collective.{group_name}"


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Declare this worker as ``rank`` of a ``world_size`` group.

    backend="host": numpy collectives through the coordinator/object plane.
    (In-mesh XLA collectives don't need a group: use ``collective.xla``.)
    """
    if backend not in ("host",):
        raise ValueError(f"unknown collective backend {backend!r}")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    coordinator_cls = ray_tpu.remote(_Coordinator)
    name = _coordinator_name(group_name)
    try:
        coordinator = coordinator_cls.options(name=name, num_cpus=0).remote(world_size)
        # Force ctor completion so a racing get_actor sees a live actor.
        ray_tpu.get(coordinator.ready.remote("__init__"))
    except ValueError:
        coordinator = ray_tpu.get_actor(name)
    _groups()[group_name] = _GroupContext(group_name, coordinator, world_size, rank)


def destroy_collective_group(group_name: str = "default") -> None:
    ctx = _groups().pop(group_name, None)
    if ctx is not None and ctx.rank == 0:
        try:
            ray_tpu.kill(ray_tpu.get_actor(_coordinator_name(group_name)))
        except ValueError:
            pass


def get_rank(group_name: str = "default") -> int:
    return _ctx(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _ctx(group_name).world_size


def _as_np(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    ctx = _ctx(group_name)
    payloads = ctx.exchange("allreduce", _as_np(tensor))
    return _REDUCERS[op]([payloads[r] for r in sorted(payloads)])


def allgather(tensor, group_name: str = "default") -> list:
    ctx = _ctx(group_name)
    payloads = ctx.exchange("allgather", _as_np(tensor))
    return [payloads[r] for r in sorted(payloads)]


def barrier(group_name: str = "default") -> None:
    _ctx(group_name).exchange("barrier", None)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM):
    ctx = _ctx(group_name)
    # Every rank fetches (slot GC needs world_size fetches; a single-fetch
    # slot could vanish before non-dst ranks observe completeness).
    payloads = ctx.exchange("reduce", _as_np(tensor))
    if ctx.rank == dst_rank:
        return _REDUCERS[op]([payloads[r] for r in sorted(payloads)])
    return tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    ctx = _ctx(group_name)
    payloads = ctx.exchange(
        "broadcast",
        _as_np(tensor) if ctx.rank == src_rank else None,
        contribute=ctx.rank == src_rank,
        expected=1,
    )
    return payloads[src_rank]


def reducescatter(tensor, group_name: str = "default", op: str = ReduceOp.SUM):
    """Reduce across ranks, then return this rank's 1/world_size chunk
    (along axis 0, which must divide evenly)."""
    ctx = _ctx(group_name)
    arr = _as_np(tensor)
    if arr.shape[0] % ctx.world_size != 0:
        raise ValueError(
            f"reducescatter axis-0 dim {arr.shape[0]} not divisible by "
            f"world_size {ctx.world_size}"
        )
    payloads = ctx.exchange("reducescatter", arr)
    reduced = _REDUCERS[op]([payloads[r] for r in sorted(payloads)])
    chunk = arr.shape[0] // ctx.world_size
    return reduced[ctx.rank * chunk : (ctx.rank + 1) * chunk]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    ctx = _ctx(group_name)
    if dst_rank == ctx.rank:
        raise ValueError("cannot send to self")
    key = ctx.next_pair_key(ctx.rank, dst_rank)
    ray_tpu.get(
        ctx.coordinator.contribute.remote(key, ctx.rank, _as_np(tensor), 1, 1)
    )


def recv(src_rank: int, group_name: str = "default", timeout: float = 120.0):
    ctx = _ctx(group_name)
    if src_rank == ctx.rank:
        raise ValueError("cannot recv from self")
    key = ctx.next_pair_key(src_rank, ctx.rank)
    deadline = time.monotonic() + timeout
    while True:
        ok, payloads = ray_tpu.get(ctx.coordinator.try_fetch.remote(key, ctx.rank))
        if ok:
            return payloads[src_rank]
        if time.monotonic() > deadline:
            raise TimeoutError(f"recv from rank {src_rank} timed out")
        time.sleep(0.002)
