"""Deterministic failpoints: named fault-injection sites.

Reference parity: the reference ships deterministic delay injection into
its event loop (``RAY_testing_asio_delay_us``, ``ray_config_def.h:706``)
plus chaos node-killer tests; this module generalizes that into named,
cluster-armable failpoints (the FreeBSD ``fail(9)`` / Rust ``fail-rs``
idiom). Load-bearing code paths call::

    from ray_tpu.util import failpoints
    ...
    failpoints.hit("agent.dispatch.before_push")

which is **zero-cost when unarmed** — one module-level dict truthiness
check, no locks, no allocation — so sites stay compiled into production
paths permanently.

Arming
------
* Environment (inherited by every spawned worker/agent process)::

      RAY_TPU_FAILPOINTS="agent.heartbeat=delay:0.5;client.recover.before_resubmit=raise,once"

* Runtime, cluster-wide, over the control plane:
  ``state.set_failpoints({...})`` / ``ray-tpu chaos arm`` →
  head ``rpc_set_failpoints`` → every agent → every live worker.

Spec grammar (one failpoint per site)::

    <action>[:<arg>][,<selector>...]

actions:
    raise[:message]   raise FailpointError(message) at the site
    delay:<seconds>   sleep that long, then continue
    hang[:<seconds>]  block (until disarmed, max <seconds>, default 60)
    kill              os._exit(1) — a hard process crash mid-protocol
    off               no-op (placeholder; equivalent to disarmed)

selectors (combinable):
    p=<float>         fire with this probability per hit (seeded RNG)
    nth=<int>         fire only on the N-th hit of the site (1-based)
    once              disarm the site after its first firing

All chaos randomness (failpoint probability, soak schedules, jitter in
network chaos) seeds from one knob — ``RAY_TPU_CHAOS_SEED`` — via
:func:`seeded_rng`, so any chaos repro is one env var away.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

# The registered site table: every `failpoints.hit("<site>")` compiled
# into the codebase MUST be listed here (`ray-tpu analyze` rule CD001),
# and every entry here must still have a live hit() site (rule CD002,
# checked repo-wide) — so chaos coverage is reviewable in one place and
# a site can't silently appear or vanish in either direction. Arming an
# UNREGISTERED site is still allowed (tests arm ad-hoc sites), but a
# production code path may only hit registered ones.
SITES = frozenset({
    # head control plane
    "head.schedule.batch",
    "head.drain.before_migrate",
    "head.restart_actor.tick",
    "head.snapshot.before_persist",
    # placement-group 2PC + reschedule coordinator (mid-2PC crashes,
    # severed prepare/commit replies, coordinator death are all
    # injectable)
    "head.pg.before_reschedule",
    "head.pg.prepare",
    "head.pg.commit",
    # node agent
    "agent.lease.push",
    "agent.dispatch.before_push",
    "agent.worker_events.upload",
    "agent.fetch.chunk",
    "agent.heartbeat",
    # spill plane (round 14): a raise-armed before_write skips that
    # object's spill (pressure stays), before_fetch fails the restore
    # (recovery falls back to recompute) — both degrade, never corrupt.
    "agent.spill.before_write",
    "agent.restore.before_fetch",
    # driver/client
    "client.dispatch.before_push",
    "client.recover.before_resubmit",
    "client.retry_submit.tick",
    "client.flush_refs.before",
    # worker
    "worker.execute.before",
    "worker.execute.after",
    # serve LLM engine (iteration-level scheduler: chaos can crash,
    # delay or hang admission/decode mid-iteration; the loop requeues
    # interrupted admissions and fails streams fast, never hangs)
    "serve.llm.before_admit",
    "serve.llm.before_step",
    # autoscaling dataset actor pool: a raise-armed site skips that
    # scale decision (the pool keeps its current size and the map
    # completes); delay models slow actor boot.
    "data.pool.before_scale",
    # fleet autoscaler execution half (round 17): tick fires once per
    # reconcile pass (delay/hang models a wedged control loop — the
    # loop must keep its cadence, not pile up), before_create injects
    # boot failures (driving the backoff/quarantine schedule), and
    # before_terminate interposes on scale-down AFTER the drain
    # completed — a raise leaves the node for the next pass to reap,
    # never un-drains it.
    "autoscaler.tick",
    "autoscaler.before_create",
    "autoscaler.before_terminate",
})

# site -> _Failpoint. `hit()` gates on plain truthiness of this dict:
# the unarmed fast path must never take a lock.
_ARMED: dict = {}
_lock = threading.Lock()


class FailpointError(RuntimeError):
    """The error a ``raise``-action failpoint injects."""


def effective_seed() -> Optional[int]:
    """The chaos seed in effect (``RAY_TPU_CHAOS_SEED``), or None when
    chaos randomness is unseeded. Printed by harnesses on failure so a
    repro is one env var away."""
    from ray_tpu.core.config import config

    seed = config.chaos_seed
    return int(seed) if seed else None


def seeded_rng(salt: str = "") -> random.Random:
    """A ``random.Random`` for chaos decisions: deterministic from
    ``RAY_TPU_CHAOS_SEED`` (+ a per-consumer salt so independent
    consumers don't replay each other's streams), OS entropy when the
    knob is unset."""
    seed = effective_seed()
    if seed is None:
        return random.Random()
    return random.Random(f"{seed}:{salt}")


class _Failpoint:
    __slots__ = ("site", "action", "arg", "prob", "nth", "once",
                 "hits", "fired", "rng", "spec")

    def __init__(self, site: str, spec: str):
        self.site = site
        self.spec = spec
        head, *selectors = [p.strip() for p in spec.split(",")]
        action, _, arg = head.partition(":")
        action = action.strip().lower()
        if action not in ("raise", "delay", "hang", "kill", "off"):
            raise ValueError(
                f"failpoint {site!r}: unknown action {action!r} "
                f"(want raise|delay|hang|kill|off)")
        self.action = action
        self.arg = arg
        if action == "delay":
            self.arg = float(arg or 0.05)
        elif action == "hang":
            self.arg = float(arg or 60.0)
        self.prob: Optional[float] = None
        self.nth: Optional[int] = None
        self.once = False
        for sel in selectors:
            if not sel:
                continue
            if sel == "once":
                self.once = True
            elif sel.startswith("p="):
                self.prob = float(sel[2:])
            elif sel.startswith("nth="):
                self.nth = int(sel[4:])
            else:
                raise ValueError(
                    f"failpoint {site!r}: unknown selector {sel!r}")
        self.hits = 0
        self.fired = 0
        self.rng = seeded_rng("failpoint:" + site)

    def should_fire(self) -> bool:
        """Caller holds _lock. Applies selectors against the hit count."""
        self.hits += 1
        if self.nth is not None and self.hits != self.nth:
            return False
        if self.prob is not None and self.rng.random() >= self.prob:
            return False
        self.fired += 1
        return True

    def describe(self) -> dict:
        return {"site": self.site, "spec": self.spec,
                "hits": self.hits, "fired": self.fired}


def hit(site: str) -> None:
    """Fault-injection site. No-op (one dict check) unless armed."""
    if not _ARMED:
        return
    fp = _ARMED.get(site)
    if fp is None:
        return
    with _lock:
        # Re-read under the lock: a concurrent disarm must win.
        fp = _ARMED.get(site)
        if fp is None or not fp.should_fire():
            return
        action, arg = fp.action, fp.arg
        if fp.once and action != "hang":
            # `hang,once` keeps the site armed THROUGH the hang (the
            # hang loop's release condition is "site disarmed") and
            # auto-disarms after it; everything else disarms now.
            _ARMED.pop(site, None)
    if action == "off":
        return
    if action == "raise":
        raise FailpointError(arg or f"failpoint {site}")
    if action == "delay":
        time.sleep(arg)
        return
    if action == "hang":
        deadline = time.monotonic() + arg
        try:
            while time.monotonic() < deadline:
                if site not in _ARMED:  # disarm releases the hang
                    return
                time.sleep(0.05)
        finally:
            if fp.once:
                with _lock:
                    if _ARMED.get(site) is fp:
                        _ARMED.pop(site, None)
        return
    if action == "kill":
        os._exit(1)


def arm(site: str, spec: str) -> None:
    """Arm (or re-arm) one site. The spec is validated here, so a bad
    spec fails at arm time at the control plane, never inside a site."""
    fp = _Failpoint(site, spec)
    with _lock:
        _ARMED[site] = fp


def disarm(site: str) -> bool:
    with _lock:
        return _ARMED.pop(site, None) is not None


def reset() -> None:
    """Disarm everything (test teardown / `ray-tpu chaos disarm --all`)."""
    with _lock:
        _ARMED.clear()


def set_failpoints(specs: dict) -> dict:
    """Batch arm/disarm: ``{site: spec}``; a None/"" spec disarms the
    site. Returns the surviving armed table (``list_armed()``).

    All-or-nothing: every spec is parsed before any table mutation, so
    one invalid spec in a batch cannot leave this process (or, through
    the head's fanout, the cluster) partially armed."""
    parsed = [(site, _Failpoint(site, spec) if spec else None)
              for site, spec in (specs or {}).items()]
    with _lock:
        for site, fp in parsed:
            if fp is None:
                _ARMED.pop(site, None)
            else:
                _ARMED[site] = fp
    return list_armed()


def list_armed() -> dict:
    """{site: {spec, hits, fired}} snapshot of this process's table."""
    with _lock:
        return {site: fp.describe() for site, fp in _ARMED.items()}


def arm_from_env() -> None:
    """Arm from ``RAY_TPU_FAILPOINTS`` (``site=spec;site=spec``): read at
    import so spawned workers/agents inherit armed sites through their
    environment with no control-plane round trip."""
    raw = os.environ.get("RAY_TPU_FAILPOINTS", "")
    if not raw:
        return
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, spec = part.partition("=")
        try:
            arm(site.strip(), spec.strip())
        except ValueError:
            # A bad env spec must not take the process down at import.
            continue


arm_from_env()
