"""multiprocessing.Pool-compatible API over tasks.

Reference parity: ``python/ray/util/multiprocessing/pool.py`` — drop-in
``Pool`` with map/starmap/apply and their async variants, backed by
``@remote`` tasks instead of OS processes.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        values = ray_tpu.get(self._refs, timeout=timeout)
        return values[0] if self._single else values

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(
            self._refs, num_returns=len(self._refs), timeout=0
        )
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Task-backed process pool. ``processes`` caps in-flight chunks."""

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or 8
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _run(self, func: Callable, args: tuple, kwargs: dict):
        initializer, initargs = self._initializer, self._initargs

        def call(*a, **kw):
            if initializer is not None:
                initializer(*initargs)
            return func(*a, **kw)

        task = ray_tpu.remote(call)
        return task.remote(*args, **kwargs)

    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return ray_tpu.get(self._run(func, args, kwds or {}))

    def apply_async(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return AsyncResult([self._run(func, args, kwds or {})], single=True)

    @staticmethod
    def _chunks(iterable: Iterable, size: int):
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, size))
            if not chunk:
                return
            yield chunk

    def _map_refs(self, func, iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)

        def run_chunk(chunk):
            if self._initializer is not None:
                self._initializer(*self._initargs)
            return [func(x) for x in chunk]

        task = ray_tpu.remote(run_chunk)
        return [task.remote(c) for c in self._chunks(items, chunksize)]

    def map(self, func, iterable, chunksize: Optional[int] = None) -> list:
        refs = self._map_refs(func, iterable, chunksize)
        return [x for chunk in ray_tpu.get(refs) for x in chunk]

    def map_async(self, func, iterable, chunksize: Optional[int] = None):
        refs = self._map_refs(func, iterable, chunksize)

        class _MapResult(AsyncResult):
            def get(self, timeout=None):
                return [x for c in ray_tpu.get(self._refs, timeout=timeout)
                        for x in c]

        return _MapResult(refs)

    def starmap(self, func, iterable, chunksize: Optional[int] = None) -> list:
        return self.map(lambda args: func(*args), iterable, chunksize)

    def imap(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, func, iterable, chunksize: int = 1):
        refs = self._map_refs(func, iterable, chunksize)
        pending = list(refs)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=None)
            for r in ready:
                yield from ray_tpu.get(r)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still open")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
