"""Utility layer over the core task/actor/object API.

Reference parity: ``python/ray/util/`` — placement groups, scheduling
strategies, ActorPool, queue, collective groups. Everything here uses only
public ``ray_tpu`` APIs (the SURVEY.md §1 layering invariant).
"""

from ray_tpu.util.placement_group import (
    PlacementGroup,
    get_current_placement_group,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Queue

__all__ = [
    "ActorPool",
    "Queue",
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "get_current_placement_group",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
]
