"""Stale shared-memory sweeper: reclaim dead runs' /dev/shm segments.

Every node agent mmaps its object store at
``/dev/shm/ray_tpu_<session>_<nodeid>`` where the session embeds the
CREATING process's pid (``s<pid>`` for standalone agents, ``c<pid>_…``
for in-process ``cluster_utils.Cluster``s, ``stress_<pid>`` for the
native stress tool). A graceful stop unlinks the segment — but a
SIGKILLed run leaves it behind, and /dev/shm is RAM: 121 GB of leaked
segments were observed after one interrupted soak, enough to OOM every
later tier-1 run on the box with no survivor to blame.

:func:`sweep_stale_shm` removes segments whose owning pid is dead. It
runs at cluster startup (``cluster_utils.Cluster``) and from
``tests/conftest.py``; swept bytes count into
``ray_tpu_shm_swept_bytes_total``. Segments whose owner is alive — or
whose name embeds no parseable pid — are never touched.
"""

from __future__ import annotations

import os
import re
from typing import Tuple

SHM_DIR = "/dev/shm"
# ray_tpu_<session>_<suffix> where session starts with the creator pid:
# s<pid>, c<pid>_<hex>, stress_<pid>.
_PID_RE = re.compile(
    r"^ray_tpu_(?:s(?P<spid>\d+)_|c(?P<cpid>\d+)_|stress_(?P<tpid>\d+))")


def _owner_pid(name: str) -> int | None:
    m = _PID_RE.match(name)
    if not m:
        return None
    for group in ("spid", "cpid", "tpid"):
        pid = m.group(group)
        if pid:
            return int(pid)
    return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    return True


def sweep_stale_shm(shm_dir: str = SHM_DIR) -> Tuple[int, int]:
    """Remove ``ray_tpu_*`` segments whose owning pid is dead; returns
    ``(segments_removed, bytes_removed)``. Best-effort by design: a
    sweep failure must never fail the startup that invoked it."""
    removed = 0
    freed = 0
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return (0, 0)
    for name in names:
        if not name.startswith("ray_tpu_"):
            continue
        pid = _owner_pid(name)
        if pid is None or pid == os.getpid() or _pid_alive(pid):
            continue
        path = os.path.join(shm_dir, name)
        try:
            size = os.path.getsize(path)
            os.unlink(path)
        except OSError:
            continue  # raced another sweeper / permissions: skip
        removed += 1
        freed += size
    if freed:
        try:
            from ray_tpu.util import metrics as _metrics

            _metrics.SHM_SWEPT_BYTES.inc(freed)
        except Exception:
            pass
    return (removed, freed)
