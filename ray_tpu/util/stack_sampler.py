"""Pure-Python stack profiling: on-demand dumps and time-sampled profiles.

Reference parity: the reference's reporter agent shells out to py-spy for
``ray stack`` and per-worker CPU flame graphs
(``dashboard/modules/reporter/reporter_agent.py``). A dependency-free
equivalent is enough here: ``sys._current_frames()`` exposes every
thread's frame from inside the process, so the worker itself serves
dump/profile RPCs — no ptrace, no external binary, works in any
container.

Three output forms per profile, all derived from the same samples:

* text report — aggregated stacks sorted by sample count (``ray stack``);
* collapsed format — ``thread;frame;...;frame count`` lines, directly
  consumable by flamegraph.pl / speedscope / inferno;
* chrome-trace events — ``ph: "X"`` slices (consecutive samples with a
  common stack prefix are coalesced into one slice per frame), the same
  event shape ``state.timeline()`` emits so a profile can be merged into
  the task timeline and opened in Perfetto.

Everything returned is plain dicts/lists/strings so profiles cross the
RPC plane natively (no pickle) and serialize straight to JSON.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Tuple

__all__ = [
    "dump_stacks",
    "sample",
    "collapsed",
    "text_report",
    "chrome_trace",
]


def _thread_names() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


def _frame_label(frame) -> str:
    code = frame.f_code
    return f"{code.co_name} ({os.path.basename(code.co_filename)}:{frame.f_lineno})"


def _frame_key(frame) -> str:
    """Aggregation key: no line number, so a function busy across several
    lines collapses into one flame-graph frame."""
    code = frame.f_code
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


def _capture(skip_idents=()) -> Dict[int, Tuple[str, ...]]:
    """One sample: {thread_ident: stack as (root, ..., leaf) frame keys}."""
    out = {}
    for ident, frame in sys._current_frames().items():
        if ident in skip_idents:
            continue
        stack: List[str] = []
        while frame is not None:
            stack.append(_frame_key(frame))
            frame = frame.f_back
        stack.reverse()
        out[ident] = tuple(stack)
    return out


def dump_stacks(header: str = "") -> str:
    """Instantaneous stack report of every thread (``ray stack`` /
    ``py-spy dump`` analog), leaf frame last."""
    names = _thread_names()
    me = threading.get_ident()
    lines: List[str] = []
    if header:
        lines.append(header)
    lines.append(
        f"pid {os.getpid()}: {len(sys._current_frames())} threads "
        f"at {time.strftime('%Y-%m-%d %H:%M:%S')}")
    for ident, frame in sorted(sys._current_frames().items()):
        marker = " (this dump)" if ident == me else ""
        lines.append(
            f"\n-- thread {names.get(ident, '?')} (ident {ident}){marker} --")
        lines.extend(
            line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


def sample(duration_s: float = 1.0, interval_s: float = 0.01) -> dict:
    """Time-sample every thread of this process for ``duration_s``.

    Returns a plain-data profile::

        {
          "pid", "duration_s", "interval_s", "num_samples",
          "threads": {name: samples_observed},
          "stacks": [{"thread", "frames": [root..leaf], "count"}, ...],
          "trace_events": [chrome "X" events, coalesced],
        }
    """
    duration_s = max(0.0, float(duration_s))
    interval_s = min(max(float(interval_s), 0.001), 1.0)
    me = threading.get_ident()
    timeline: List[Tuple[float, Dict[int, Tuple[str, ...]]]] = []
    t0 = time.perf_counter()
    deadline = t0 + duration_s
    while True:
        now = time.perf_counter()
        timeline.append((now - t0, _capture(skip_idents=(me,))))
        if now >= deadline:
            break
        time.sleep(min(interval_s, max(0.0, deadline - now)))
    names = _thread_names()

    agg: Dict[Tuple[str, Tuple[str, ...]], int] = {}
    per_thread: Dict[str, int] = {}
    for _ts, stacks in timeline:
        for ident, frames in stacks.items():
            name = names.get(ident, f"thread-{ident}")
            per_thread[name] = per_thread.get(name, 0) + 1
            agg[(name, frames)] = agg.get((name, frames), 0) + 1

    stacks_out = [
        {"thread": name, "frames": list(frames), "count": count}
        for (name, frames), count in sorted(
            agg.items(), key=lambda kv: -kv[1])
    ]
    return {
        "pid": os.getpid(),
        "duration_s": round(time.perf_counter() - t0, 4),
        "interval_s": interval_s,
        "num_samples": len(timeline),
        "threads": per_thread,
        "stacks": stacks_out,
        "trace_events": _trace_events(timeline, names, interval_s),
    }


def _trace_events(timeline, names, interval_s) -> List[dict]:
    """Coalesce consecutive samples sharing a stack prefix into one
    chrome-trace "X" slice per frame (what py-spy's chrometrace format
    does); compatible with the events ``state.timeline()`` emits."""
    events: List[dict] = []
    open_frames: Dict[int, List[Tuple[str, float]]] = {}

    def close_from(ident, depth, now):
        cur = open_frames.get(ident, [])
        for frame, start in reversed(cur[depth:]):
            events.append({
                "name": frame,
                "cat": "stack_sample",
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(1.0, (now - start) * 1e6),
                "pid": f"pid-{os.getpid()}",
                "tid": names.get(ident, f"thread-{ident}"),
            })
        del cur[depth:]

    end_ts = (timeline[-1][0] + interval_s) if timeline else 0.0
    for ts, stacks in timeline:
        for ident in set(open_frames) | set(stacks):
            new = stacks.get(ident, ())
            cur = open_frames.setdefault(ident, [])
            i = 0
            while i < len(cur) and i < len(new) and cur[i][0] == new[i]:
                i += 1
            close_from(ident, i, ts)
            for frame in new[i:]:
                cur.append((frame, ts))
    for ident in list(open_frames):
        close_from(ident, 0, end_ts)
    events.sort(key=lambda e: e["ts"])
    return events


def collapsed(profile: dict) -> str:
    """Flame-graph collapsed format: ``thread;root;...;leaf count``."""
    lines = [
        ";".join([s["thread"], *s["frames"]]) + f" {s['count']}"
        for s in profile.get("stacks", [])
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def text_report(profile: dict) -> str:
    """Human-readable aggregated report, hottest stacks first."""
    n = max(1, profile.get("num_samples", 1))
    lines = [
        f"pid {profile.get('pid', '?')}: {profile.get('num_samples', 0)} "
        f"samples over {profile.get('duration_s', 0.0):.2f}s "
        f"(interval {profile.get('interval_s', 0.0) * 1000:.0f}ms)"
    ]
    for s in profile.get("stacks", []):
        pct = 100.0 * s["count"] / n
        lines.append(
            f"\n{s['count']:>5} samples ({pct:4.1f}%) thread {s['thread']}")
        lines.extend(f"    {frame}" for frame in s["frames"])
    return "\n".join(lines) + "\n"


def chrome_trace(profile: dict) -> List[dict]:
    """The profile's chrome-trace events (mergeable with
    ``state.timeline()`` output; open in Perfetto / chrome://tracing)."""
    return list(profile.get("trace_events", []))
