"""Client side of the Ray Client analog (``python/ray/util/client``).

Implements the process-wide Backend surface entirely over RPC to a
ClientProxyServer — no shared memory, no cluster membership. Selected by
``ray_tpu.init(address="ray://host:port")``.

Ref lifetime: every ObjectRef this backend mints carries a finalizer that
batches a release RPC to the proxy (which holds the real refs); a
heartbeat thread keeps the session alive, and nested refs deserialized
out of fetched values are re-pinned server-side before use.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Any, Callable, Sequence

from ray_tpu.cluster.rpc import ConnectionLost, RpcClient
from ray_tpu.core import serialization as ser
from ray_tpu.core.object_ref import ObjectRef


class ClientBackend:
    def __init__(self, address: str):
        self.address = address
        self.rpc = RpcClient(address)
        self.session_id = f"cs:{os.getpid()}:{os.urandom(4).hex()}"
        hello = self.rpc.call("client_hello", self.session_id)
        self._ttl = float(hello.get("ttl_s", 60.0))
        self._closed = False
        # MUST be reentrant: _queue_release runs as a weakref.finalize
        # callback, so a GC pass can fire it on whatever thread is
        # allocating — including the heartbeat thread while it holds
        # this lock (extend() allocates). A plain Lock self-deadlocks
        # there (the PR-5 local-backend bug class; ray-tpu analyze
        # FS001 now guards this).
        self._release_lock = threading.RLock()
        self._pending_release: list[str] = []
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    # -- plumbing ----------------------------------------------------------

    def _call(self, method: str, *args, timeout: float | None = None):
        return self.rpc.call(
            method, self.session_id, *args, timeout=timeout)

    def _heartbeat_loop(self):
        interval = max(1.0, self._ttl / 4)
        while not self._closed:
            threading.Event().wait(interval)
            if self._closed:
                return
            # Piggyback batched ref releases on the heartbeat.
            with self._release_lock:
                batch, self._pending_release = self._pending_release, []
            try:
                if batch:
                    self._call("client_release", batch)
                self._call("client_ping")
            except (ConnectionLost, OSError):
                with self._release_lock:
                    self._pending_release.extend(batch)

    def make_ref(self, oid: str, owner: str = "") -> ObjectRef:
        ref = ObjectRef(oid, owner)
        weakref.finalize(ref, self._queue_release, oid)
        return ref

    def _queue_release(self, oid: str):
        if self._closed:
            return
        with self._release_lock:
            self._pending_release.append(oid)

    def on_ref_deserialized(self, oid: str, owner: str) -> ObjectRef:
        """A fetched value contained a nested ref: pin it server-side so
        it outlives the value it rode in on."""
        try:
            self._call("client_hold", oid)
        except (ConnectionLost, OSError):
            pass
        return self.make_ref(oid, owner)

    # -- object plane ------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        oid = self._call("client_put", ser.dumps(value))
        return self.make_ref(oid)

    # An untimed get/wait must not ride one unbounded RPC: the transport's
    # per-connection socket default (60s) would sever it under a long
    # task. Block in bounded wait slices instead, then fetch.
    _SLICE_S = 20.0

    def _wait_oids(self, oids, num_returns, timeout, fetch_local):
        if timeout is not None:
            return self._call(
                "client_wait", oids, num_returns, timeout, fetch_local,
                timeout=timeout + 15.0)
        while True:
            ready, rest = self._call(
                "client_wait", oids, num_returns, self._SLICE_S,
                fetch_local, timeout=self._SLICE_S + 15.0)
            if len(ready) >= num_returns:
                return ready, rest

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        oids = [r.id for r in refs]
        uniq = list(dict.fromkeys(oids))
        _ready, rest = self._wait_oids(uniq, len(uniq), timeout, True)
        if rest:
            from ray_tpu.core.object_ref import GetTimeoutError

            raise GetTimeoutError(
                f"{len(rest)}/{len(uniq)} objects not ready "
                f"within {timeout}s"
            )
        # Everything exists server-side now: the fetch itself is quick.
        blob = self._call("client_get", oids, 30.0, timeout=60.0)
        return ser.loads(blob)

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        by_id = {r.id: r for r in refs}
        ready, rest = self._wait_oids(
            [r.id for r in refs], num_returns, timeout, fetch_local)
        return [by_id[o] for o in ready], [by_id[o] for o in rest]

    # -- tasks / actors ----------------------------------------------------

    def submit_task(self, func: Callable, args: tuple, kwargs: dict,
                    **options) -> list[ObjectRef]:
        blob = ser.dumps((func, args, kwargs, options))
        oids = self._call("client_submit_task", blob)
        return [self.make_ref(o) for o in oids]

    def create_actor(self, cls: type, args: tuple, kwargs: dict,
                     **options) -> str:
        blob = ser.dumps((cls, args, kwargs, options))
        return self._call("client_create_actor", blob)

    def submit_actor_task(self, actor_id: str, method_name: str,
                          args: tuple, kwargs: dict, *,
                          num_returns: int = 1,
                          **options) -> list[ObjectRef]:
        options["num_returns"] = num_returns
        blob = ser.dumps((args, kwargs, options))
        oids = self._call(
            "client_submit_actor_task", actor_id, method_name, blob)
        return [self.make_ref(o) for o in oids]

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        self._call("client_kill_actor", actor_id, no_restart)

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._call("client_cancel", ref.id, force)

    def get_named_actor(self, name: str) -> str:
        return self._call("client_get_named_actor", name)

    # -- introspection / kv ------------------------------------------------

    def cluster_resources(self) -> dict:
        return self._call("client_cluster_resources")

    def available_resources(self) -> dict:
        return self._call("client_available_resources")

    def nodes(self) -> list:
        return self._call("client_nodes")

    def kv_put(self, key, value, overwrite=True):
        return self._call("client_kv", "put", key, value, overwrite)

    def kv_get(self, key):
        return self._call("client_kv", "get", key)

    def kv_del(self, key):
        return self._call("client_kv", "del", key)

    def kv_keys(self, prefix=""):
        return self._call("client_kv", "keys", prefix)

    # -- serve streaming ---------------------------------------------------

    def serve_stream(self, deployment: str, args: tuple, kwargs: dict,
                     meta=None):
        """Token-streaming serve call: the proxy runs the routed stream
        server-side (shm prompt handoff included) and forwards each
        chunk over a dedicated server-streaming RPC connection, so many
        concurrent client streams multiplex cleanly. Server-side typed
        sheds (RequestShedError) re-raise here."""
        blob = ser.dumps((tuple(args), dict(kwargs or {}), meta))

        def gen():
            # The per-frame timeout only needs to outlive the proxy's
            # keepalive cadence (20s), not the stream's total life — a
            # deep-queued stream stays quiet for minutes while the
            # proxy's keepalive frames keep the socket warm.
            for item in self.rpc.call_stream(
                    "client_serve_stream", self.session_id, deployment,
                    blob, timeout=90.0):
                if isinstance(item, dict) \
                        and item.get("__stream_keepalive__"):
                    continue
                yield item

        return gen()

    # -- lifecycle ---------------------------------------------------------

    def shutdown(self) -> None:
        self._closed = True
        try:
            self._call("client_bye")
        except (ConnectionLost, OSError):
            pass
        self.rpc.close()
