"""Server side of the Ray Client analog (``util/client/server/proxier.py``).

Hosts ONE driver-style ClusterBackend and proxies a narrow RPC surface to
remote clients. Per-session bookkeeping pins every ObjectRef handed to a
client until the client releases it (or its session expires), so the
cluster's distributed ref-counting sees the proxy as the holder — remote
clients never participate in shm or the ref protocol directly.
"""

from __future__ import annotations

import threading
import time

from ray_tpu.cluster.rpc import RpcServer
from ray_tpu.core import serialization as ser

SESSION_TTL_S = 60.0


class _Session:
    __slots__ = ("refs", "actors", "last_seen")

    def __init__(self):
        # oid -> [ObjectRef, pin_count]: a COUNT, not a set — the client
        # may hold several distinct refs to one oid (each with its own
        # release finalizer), and the pin must survive until the LAST one
        # is gone.
        self.refs: dict[str, list] = {}
        # Actors created by this session: killed when it ends (reference
        # Ray Client tears down the session's driver state).
        self.actors: set[str] = set()
        self.last_seen = time.monotonic()


class ClientProxyServer:
    def __init__(self, head_address: str, host: str = "127.0.0.1",
                 port: int = 0, session_ttl_s: float = SESSION_TTL_S):
        from ray_tpu.cluster.client import ClusterBackend

        self.backend = ClusterBackend(head_address)
        self._sessions: dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._ttl = session_ttl_s
        self._stop = threading.Event()
        self._server = RpcServer(self, host, port)
        self.address = self._server.address
        threading.Thread(target=self._reap_loop, daemon=True).start()

    def shutdown(self):
        self._stop.set()
        self._server.stop()
        self.backend.shutdown()

    # -- sessions ----------------------------------------------------------

    def _session(self, sid: str) -> _Session:
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                s = self._sessions[sid] = _Session()
            s.last_seen = time.monotonic()
            return s

    def _reap_loop(self):
        while not self._stop.wait(5.0):
            cutoff = time.monotonic() - self._ttl
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if s.last_seen < cutoff]
                sessions = [self._sessions.pop(sid) for sid in dead]
            for s in sessions:
                self._teardown(s)

    def _teardown(self, s: _Session):
        """End-of-session cleanup: dropping the refs releases the proxy's
        holds (the cluster ref-counter frees what nothing else holds),
        and the session's UNNAMED actors are killed — a crashed client
        must not leak actor workers forever. Named actors survive: they
        are discoverable (and possibly in use) by other sessions, and a
        client whose link blipped past the TTL can find them again."""
        with self._lock:
            actors = list(s.actors)
            s.refs.clear()
        for actor_id in actors:
            try:
                info = self.backend._actor_info(actor_id, refresh=True)
            except Exception:
                # Unknown state (head slow/unreachable) must fail SAFE:
                # skipping the kill leaks at worst one worker; killing a
                # named actor another session uses breaks them for real.
                continue
            if info.get("name"):
                continue
            try:
                self.backend.kill_actor(actor_id)
            except Exception:
                pass

    # Ref pin bookkeeping runs under self._lock: per-connection server
    # threads race (the client's heartbeat releases concurrently with its
    # main thread's get/submit), and count updates are check-then-act.

    def _track(self, sid: str, refs) -> list[str]:
        s = self._session(sid)
        oids = []
        with self._lock:
            for r in refs:
                entry = s.refs.get(r.id)
                if entry is None:
                    s.refs[r.id] = [r, 1]
                else:
                    entry[1] += 1
                oids.append(r.id)
        return oids

    # -- rpc surface -------------------------------------------------------

    def rpc_client_hello(self, sid: str):
        self._session(sid)
        return {"server": "ray_tpu-client-proxy", "ttl_s": self._ttl}

    def rpc_client_ping(self, sid: str):
        self._session(sid)
        return True

    def rpc_client_bye(self, sid: str):
        with self._lock:
            s = self._sessions.pop(sid, None)
        if s is not None:
            self._teardown(s)
        return True

    def rpc_client_put(self, sid: str, blob: bytes) -> str:
        value = ser.loads(blob)
        ref = self.backend.put(value)
        return self._track(sid, [ref])[0]

    def _refs_of(self, s: _Session, oids: list) -> list:
        with self._lock:
            entries = [s.refs.get(o) for o in oids]
        return [
            (e[0] if e is not None else self.backend.make_ref(o))
            for e, o in zip(entries, oids)
        ]

    def rpc_client_get(self, sid: str, oids: list, timeout) -> bytes:
        s = self._session(sid)
        values = self.backend.get(self._refs_of(s, oids), timeout)
        return ser.dumps(values)

    def rpc_client_hold(self, sid: str, oid: str):
        """A client deserialized a nested ref: pin it for the session."""
        self._track(sid, [self.backend.make_ref(oid)])
        return True

    def rpc_client_release(self, sid: str, oids: list):
        s = self._session(sid)
        with self._lock:
            for o in oids:
                entry = s.refs.get(o)
                if entry is not None:
                    entry[1] -= 1
                    if entry[1] <= 0:
                        del s.refs[o]
        return True

    def rpc_client_submit_task(self, sid: str, blob: bytes) -> list:
        func, args, kwargs, options = ser.loads(blob)
        refs = self.backend.submit_task(func, args, kwargs, **options)
        return self._track(sid, refs)

    def rpc_client_create_actor(self, sid: str, blob: bytes) -> str:
        cls, args, kwargs, options = ser.loads(blob)
        actor_id = self.backend.create_actor(cls, args, kwargs, **options)
        self._session(sid).actors.add(actor_id)
        return actor_id

    def rpc_client_submit_actor_task(self, sid: str, actor_id: str,
                                     method: str, blob: bytes) -> list:
        args, kwargs, options = ser.loads(blob)
        refs = self.backend.submit_actor_task(
            actor_id, method, args, kwargs, **options)
        return self._track(sid, refs)

    def rpc_client_wait(self, sid: str, oids: list, num_returns: int,
                        timeout, fetch_local: bool):
        s = self._session(sid)
        ready, rest = self.backend.wait(
            self._refs_of(s, oids), num_returns, timeout, fetch_local)
        return [r.id for r in ready], [r.id for r in rest]

    def rpc_client_kill_actor(self, sid: str, actor_id: str,
                              no_restart: bool):
        return self.backend.kill_actor(actor_id, no_restart)

    def rpc_client_cancel(self, sid: str, oid: str, force: bool):
        s = self._session(sid)
        ref = self._refs_of(s, [oid])[0]
        return self.backend.cancel(ref, force)

    def rpc_client_get_named_actor(self, sid: str, name: str) -> str:
        return self.backend.get_named_actor(name)

    def rpc_client_cluster_resources(self, sid: str):
        return self.backend.cluster_resources()

    def rpc_client_available_resources(self, sid: str):
        return self.backend.available_resources()

    def rpc_client_nodes(self, sid: str):
        return self.backend.nodes()

    def rpc_client_kv(self, sid: str, op: str, *args):
        return getattr(self.backend, "kv_" + op)(*args)

    # Prompt payloads at or above this many tokens ride the shared-memory
    # object store instead of the actor-call frame: the proxy puts the
    # list once and hands the replica an ObjectRef — a same-node shm
    # read (zero-copy mmap), not a second serialize/copy over RPC.
    PROMPT_SHM_MIN_TOKENS = 512

    def rpc_client_serve_stream(self, sid: str, deployment: str,
                                blob: bytes):
        """Server-streaming serve call (``handle.stream()`` over
        ``ray://``): a generator handler — the RPC layer ships one frame
        per yielded token chunk, so N concurrent clients each hold their
        own streaming connection while the proxy multiplexes onto the
        ONE driver-style backend. Typed errors (RequestShedError from a
        deadline dying mid-decode) propagate to the client as the
        stream's terminal exception."""
        from ray_tpu.serve import _private as sp

        args, kwargs, meta = ser.loads(blob)
        self._session(sid)
        prompt_ref = None
        if (args and isinstance(args[0], (list, tuple))
                and len(args[0]) >= self.PROMPT_SHM_MIN_TOKENS):
            prompt_ref = self.backend.put(list(args[0]))
            args = (prompt_ref,) + tuple(args[1:])

        def gen():
            # The closure keeps prompt_ref pinned until the engine has
            # fetched it (the submit round-trip completes before the
            # first yield arrives back). keepalive frames flow while
            # the stream sits in a deep admission queue (TTFT can be
            # minutes there) so the client's socket never starves.
            _pin = prompt_ref  # noqa: F841
            yield from sp.stream_call(
                deployment, tuple(args), dict(kwargs or {}), meta,
                backend=self.backend, keepalive_every=20.0)

        return gen()
