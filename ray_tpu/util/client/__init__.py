"""Ray Client analog: connect to a cluster from a process that is NOT on
a cluster machine (``ray://host:port``).

Reference: ``python/ray/util/client`` + ``util/client/server/proxier.py``
— a server-side proxy hosts the real driver state; the remote client
speaks a narrow RPC surface and never needs shared memory access.

    # on a cluster machine (or via `cli client-server`):
    from ray_tpu.util.client import ClientProxyServer
    srv = ClientProxyServer(head_address)

    # anywhere that can reach srv.address:
    ray_tpu.init(address=f"ray://{srv.address}")
"""

from ray_tpu.util.client.server import ClientProxyServer
from ray_tpu.util.client.backend import ClientBackend

__all__ = ["ClientProxyServer", "ClientBackend"]
