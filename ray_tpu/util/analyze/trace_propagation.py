"""Pass 11 — trace-propagation span hygiene (TP): every span closes.

PR 18 threads request traces across the serve router, the LLM engine
loop and both backends with *manual* spans (``tracing.start_span`` /
``finish_span``) wherever a context manager can't express the lifetime
— generator frames that suspend across yields, engine-lock phase
transitions, spans handed between threads. Manual spans trade the
``with`` block's guaranteed close for three new leak shapes, which this
pass makes static:

* **TP001** — ``start_span`` bound to a local name that is *never*
  passed to ``finish_span`` and never escapes the function (not
  returned, yielded, stored on an object, or handed to another call).
  The span can literally never be closed: it stays open forever and
  the trace it belongs to never finalizes (the assembler waits out its
  quiet window on every request).
* **TP002** — a locally-opened span whose every ``finish_span`` sits in
  straight-line flow: one exception between open and close leaks the
  span *and* loses the error status the trace store keys tail-sampling
  on. Exception-safe means a finish in a ``finally``, or the manual
  equivalent (a finish in an ``except`` handler paired with one in
  normal flow — the engine-loop idiom, where the error path must stamp
  ``ERROR:`` before re-raising).
* **TP003** — ``tracing.span(...)`` / ``tracing.start_span(...)`` as a
  bare expression statement: the span is created and the handle
  immediately discarded, so it is unclosable from birth. ``span()``
  must be entered (``with``) and ``start_span``'s return value kept.

Spans stored on objects (``self._step_span``, ``req.span``) hand their
lifetime to another scope this per-function pass can't see — those
sites are exempt here; the runtime ``dropped_spans`` counter and the
trace store's quiet-window eviction stats cover that residue.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import callee_name, receiver_of

# The tracing module rides in under either name (serve imports it as
# `tracing`, train as `_tracing`).
_TRACING_ALIASES = frozenset({"tracing", "_tracing"})

# tracing.py itself opens and closes spans internally (the span()
# context manager is built from start/finish); it is the implementation
# of the contract, not a client of it.
_SELF_MODULES = ("util/tracing.py",)


def _is_tracing_call(call: ast.Call, names: Set[str]) -> bool:
    """``tracing.<name>(...)`` / ``_tracing.<name>(...)``."""
    if callee_name(call) not in names:
        return False
    recv = receiver_of(call)
    return isinstance(recv, ast.Name) and recv.id in _TRACING_ALIASES


def _find_start_span(expr: ast.expr) -> Optional[ast.Call]:
    """The ``start_span`` call inside an assignment's value, seeing
    through the guard idiom ``x = tracing.start_span(...) if carried
    else None`` (and nothing deeper — a span built inside a
    comprehension or lambda has its own frame)."""
    candidates = [expr]
    if isinstance(expr, ast.IfExp):
        candidates = [expr.body, expr.orelse]
    for c in candidates:
        if isinstance(c, ast.Call) and _is_tracing_call(c, {"start_span"}):
            return c
    return None


class _SpanInfo:
    __slots__ = ("name", "line", "finishes", "escaped")

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        # Flow contexts each finish_span(<name>) was seen in:
        # "finally" / "except" / "normal".
        self.finishes: Set[str] = set()
        self.escaped = False


class _FnScanner:
    """One function body walk tracking finally/except flow context.

    Nested function/class definitions are skipped — ``all_functions``
    hands each of those to the pass as its own scope, and a span
    captured by a closure counts as escaped anyway.
    """

    def __init__(self, sink: FindingSink, scope: str):
        self.sink = sink
        self.scope = scope
        self.spans: Dict[str, _SpanInfo] = {}

    # -- driver -------------------------------------------------------

    def scan(self, fn: ast.AST) -> None:
        self._walk(fn.body, ctx="normal")
        for info in self.spans.values():
            self._judge(info)

    def _judge(self, info: _SpanInfo) -> None:
        if info.escaped:
            return  # lifetime handed elsewhere; out of per-fn scope
        if not info.finishes:
            self.sink.emit(
                "TP001", info.line, self.scope,
                f"never_finished:{info.name}",
                f"span '{info.name}' is opened with start_span but "
                f"never passed to finish_span and never leaves this "
                f"function: it can never be closed, so its trace "
                f"never finalizes",
                "finish_span it (in a finally), or use the "
                "tracing.span(...) context manager")
            return
        if "finally" in info.finishes:
            return
        if "except" in info.finishes and "normal" in info.finishes:
            # The manual pair: error path stamps ERROR and re-raises,
            # success path closes OK.
            return
        self.sink.emit(
            "TP002", info.line, self.scope,
            f"unsafe_finish:{info.name}",
            f"span '{info.name}' is only finished in straight-line "
            f"flow: an exception between start_span and finish_span "
            f"leaks the span and drops the ERROR status tail-sampling "
            f"keys on",
            "move the finish into a finally, or pair an "
            "except-handler finish (ERROR status) with the "
            "normal-flow one")

    # -- statement walk -----------------------------------------------

    def _walk(self, stmts, ctx: str) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                # Own scope; a span reaching in there is a capture.
                self._mark_escapes_in(stmt, skip_call=None)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(stmt.body, ctx)
                for h in stmt.handlers:
                    self._walk(h.body, "except")
                self._walk(stmt.orelse, ctx)
                self._walk(stmt.finalbody, "finally")
                continue
            self._scan_stmt(stmt, ctx)
            if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                self._walk(stmt.body, ctx)
                self._walk(stmt.orelse, ctx)
            elif isinstance(stmt, ast.If):
                self._walk(stmt.body, ctx)
                self._walk(stmt.orelse, ctx)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._walk(stmt.body, ctx)

    def _scan_stmt(self, stmt: ast.stmt, ctx: str) -> None:
        # TP003: span created and handle discarded on the spot.
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if _is_tracing_call(call, {"span", "start_span"}):
                kind = callee_name(call)
                self.sink.emit(
                    "TP003", call.lineno, self.scope,
                    f"discarded:{call.lineno}",
                    f"tracing.{kind}(...) as a bare statement discards "
                    f"the span handle: the span is unclosable from "
                    f"birth",
                    "enter span() with `with`, or keep start_span's "
                    "return value and finish_span it")
                return
        # New tracked span: `name = tracing.start_span(...)`.
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            call = _find_start_span(stmt.value)
            if call is not None and isinstance(tgt, ast.Name):
                # Rebinding reuses the slot: the open/finish pattern is
                # judged over the whole function (the reopen idiom
                # finishes the old one through the same name).
                if tgt.id not in self.spans:
                    self.spans[tgt.id] = _SpanInfo(tgt.id, call.lineno)
                return
            if call is not None:
                return  # attribute/subscript target: escaped by design
        # finish_span(<name>) / escapes, in this statement's OWN
        # expressions. Compound statements contribute only their
        # headers — _walk recurses into their bodies carrying the
        # correct flow context (a finish inside a nested finally must
        # not also register as "normal" at the enclosing level).
        if isinstance(stmt, (ast.If, ast.While)):
            self._mark_uses(stmt.test, ctx)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._mark_uses(stmt.iter, ctx)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._mark_uses(item.context_expr, ctx)
        else:
            self._mark_uses(stmt, ctx)

    # -- name uses ----------------------------------------------------

    def _mark_uses(self, stmt: ast.stmt, ctx: str) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                self._mark_escapes_in(node, skip_call=None)
                continue
            if isinstance(node, ast.Call) and \
                    _is_tracing_call(node, {"finish_span"}):
                if node.args and isinstance(node.args[0], ast.Name):
                    info = self.spans.get(node.args[0].id)
                    if info is not None:
                        info.finishes.add(ctx)
                # Other finish args in the same call escape normally.
                for extra in node.args[1:]:
                    self._escape_expr(extra)
                continue
            self._escape_node(node)

    def _escape_node(self, node: ast.AST) -> None:
        """Conservative escape: the span name used anywhere that could
        hand its lifetime elsewhere — call argument, return/yield,
        store into an attribute/subscript/container."""
        if isinstance(node, ast.Call):
            for a in list(node.args) + [k.value for k in node.keywords]:
                self._escape_expr(a)
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            if node.value is not None:
                self._escape_expr(node.value)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    self._escape_expr(node.value)
        elif isinstance(node, (ast.List, ast.Tuple, ast.Set, ast.Dict)):
            self._escape_expr(node)

    def _escape_expr(self, expr: ast.expr) -> None:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name):
                info = self.spans.get(n.id)
                if info is not None:
                    info.escaped = True

    def _mark_escapes_in(self, node: ast.AST, skip_call) -> None:
        """A nested def/lambda capturing a span name owns it now."""
        for n in ast.walk(node):
            if isinstance(n, ast.Name):
                info = self.spans.get(n.id)
                if info is not None:
                    info.escaped = True


@analysis_pass("trace-propagation")
def trace_propagation_pass(mod: ParsedModule) -> List:
    if mod.relpath.replace("\\", "/").endswith(_SELF_MODULES):
        return []
    sink = FindingSink(mod.relpath)
    model = mod.model()
    for cm, fn, scope in model.functions():
        scanner = _FnScanner(sink, scope)
        scanner.scan(fn)
    return sink.findings
