"""Pass 7 — daemon-loop survivability (DL): one exception, one loop.

Every plane of this system hangs off a handful of forever-loops —
reaper, heartbeat, flusher, drain coordinator, leak sweeper, the LLM
engine scheduler. PR 5 found (at soak time) that a partitioned head
killed the agent's reap loop through one uncaught ``_store_task_error``;
the PR-13 engine loop survives only because review added the blanket
try/except by hand. This pass makes both halves of that discipline
static:

* **DL001** — a daemon loop body performs RPC/IO (``.call`` /
  ``.call_stream``, sqlite ``commit``) outside any ``try`` *inside the
  loop* whose handler survives the failure (catches a connection-ish
  or broad exception without re-raising/breaking). One transient
  network error permanently kills the thread — heartbeats stop, the
  store never flushes again, and nothing restarts it.
* **DL002** — a broad except handler inside a daemon loop swallows
  without COUNTING: the loop survives, invisibly. Every survival
  handler must tick ``ray_tpu_loop_restarts_total{loop}`` (the
  ``metrics.count_loop_restart(<loop>)`` helper) so a loop stuck in a
  crash-restart cycle shows on the federated scrape instead of
  burning a core silently.

A *daemon loop* is a ``while True`` / ``while not <stop-flag>`` loop
inside a function that is (a) a ``threading.Thread`` target somewhere
in the module, or (b) named like one (``*_loop`` / ``*_main`` or a
``loop``/``flusher``/``monitor``/``sweeper``/``watcher``/``reaper``/
``coordinator`` name). Bounded retry loops (``for``), and loops in
ordinary request handlers, are out of scope — RT owns retries.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import (
    _expr_calls,
    callee_name,
    receiver_of,
)

_LOOPY_NAME_PARTS = ("loop", "flusher", "monitor", "sweeper", "watcher",
                     "reaper", "coordinator")
_SURVIVAL_EXCEPTS = frozenset({
    "", "Exception", "BaseException", "ConnectionLost", "OSError",
    "IOError", "RpcError", "ConnectionError", "TimeoutError",
})
_BROAD_EXCEPTS = frozenset({"", "Exception", "BaseException"})


def _thread_targets(tree: ast.Module) -> Set[str]:
    """Leaf names handed to ``Thread(target=...)`` anywhere in the
    module (``self._run`` -> ``_run``; bare closures by name too)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and callee_name(node) == "Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            v = kw.value
            if isinstance(v, ast.Attribute):
                out.add(v.attr)
            elif isinstance(v, ast.Name):
                out.add(v.id)
    return out


def _is_daemon_fn(name: str, targets: Set[str]) -> bool:
    if name in targets:
        return True
    low = name.lower()
    if low.endswith(("_loop", "_main")):
        return True
    return any(part in low for part in _LOOPY_NAME_PARTS)


def _is_forever_loop(node: ast.While) -> bool:
    """``while True`` or ``while not <stop flag>`` — the daemon shape
    (a ``while work:`` drain loop terminates on its own)."""
    test = node.test
    if isinstance(test, ast.Constant) and test.value is True:
        return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = test.operand
        leaf = ""
        if isinstance(inner, ast.Attribute):
            leaf = inner.attr
        elif isinstance(inner, ast.Name):
            leaf = inner.id
        elif isinstance(inner, ast.Call):
            leaf = callee_name(inner)
            recv = receiver_of(inner)
            if leaf in ("is_set", "get") and isinstance(
                    recv, ast.Attribute):
                leaf = recv.attr
        return "stop" in leaf.lower() or "shutdown" in leaf.lower() \
            or "closed" in leaf.lower()
    return False


def _handler_types(handler: ast.ExceptHandler) -> Set[str]:
    t = handler.type
    if t is None:
        return {""}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out: Set[str] = set()
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _handler_guards(handler: ast.ExceptHandler) -> bool:
    """For DL001 the handler protects the THREAD as long as it doesn't
    unconditionally re-raise: break/return are controlled exits (the
    loop ends on purpose), not a crash nothing restarts."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Raise):
            return False
    return True


def _handler_reenters(handler: ast.ExceptHandler) -> bool:
    """For DL002 the handler must RE-ENTER the iteration (swallow and
    keep looping) for the restart counter to be owed: a handler that
    exits the loop (raise/return/break on its only path) isn't a
    survival point."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Raise, ast.Return, ast.Break)):
            return False
    return True


def _handler_counts_restart(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Attribute) and \
                "LOOP_RESTARTS" in node.attr:
            return True
        if isinstance(node, ast.Name) and "LOOP_RESTARTS" in node.id:
            return True
        if isinstance(node, ast.Call) and \
                "loop_restart" in callee_name(node):
            return True
    return False


def _is_io_call(node: ast.Call) -> bool:
    name = callee_name(node)
    if name in ("call", "call_stream"):
        return receiver_of(node) is not None
    if name == "commit":
        return receiver_of(node) is not None
    return False


class _LoopScanner:
    """Walk one daemon loop body tracking the guarding tries."""

    def __init__(self, sink: FindingSink, scope: str,
                 loop_name: str):
        self.sink = sink
        self.scope = scope
        self.loop_name = loop_name

    def scan(self, loop: ast.While) -> None:
        self._walk(loop.body, guarded=False)

    def _walk(self, stmts, guarded: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # deferred execution
            if isinstance(stmt, ast.Try):
                surviving = [
                    h for h in stmt.handlers
                    if _handler_types(h) & _SURVIVAL_EXCEPTS
                    and _handler_guards(h)]
                self._walk(stmt.body, guarded or bool(surviving))
                for h in stmt.handlers:
                    if _handler_types(h) & _BROAD_EXCEPTS \
                            and _handler_reenters(h) \
                            and not _handler_counts_restart(h):
                        self.sink.emit(
                            "DL002", h.lineno, self.scope,
                            f"swallow:{h.lineno}",
                            f"daemon loop {self.loop_name} survives an "
                            f"exception here without counting it: a "
                            f"crash-restart cycle in this loop is "
                            f"invisible on the scrape (it just burns "
                            f"a core)",
                            "tick metrics.count_loop_restart("
                            f"'{self.loop_name}') in the handler (the "
                            "ray_tpu_loop_restarts_total family)")
                    self._walk(h.body, guarded)
                self._walk(stmt.orelse, guarded or bool(surviving))
                self._walk(stmt.finalbody, guarded)
                continue
            # IO in this statement's own expressions (nested statements
            # recurse below with their own guard state).
            if not guarded:
                for node in _expr_calls(stmt):
                    if isinstance(node, ast.Call) and _is_io_call(node):
                        self.sink.emit(
                            "DL001", node.lineno, self.scope,
                            f"io:{node.lineno}",
                            f"RPC/IO in daemon loop {self.loop_name} "
                            f"outside any surviving try/except: one "
                            f"transient failure (a partitioned peer, a "
                            f"reconnect blip) permanently kills this "
                            f"thread and nothing restarts it",
                            "wrap the loop body in try/except, count "
                            "the failure via metrics.count_loop_"
                            "restart(...), and continue")
            if isinstance(stmt, (ast.While, ast.For)):
                self._walk(stmt.body, guarded)
                self._walk(stmt.orelse, guarded)
            elif isinstance(stmt, ast.If):
                self._walk(stmt.body, guarded)
                self._walk(stmt.orelse, guarded)
            elif isinstance(stmt, (ast.With,)):
                self._walk(stmt.body, guarded)


@analysis_pass("daemon-loop")
def daemon_loop_pass(mod: ParsedModule) -> List:
    sink = FindingSink(mod.relpath)
    targets = _thread_targets(mod.tree)
    model = mod.model()
    for cm, fn, scope in model.functions():
        if isinstance(fn, ast.AsyncFunctionDef):
            continue  # asyncio loops have their own supervision story
        if not _is_daemon_fn(fn.name, targets):
            continue
        loop_name = scope
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.While) and _is_forever_loop(stmt):
                _LoopScanner(sink, scope, loop_name).scan(stmt)
    return sink.findings
