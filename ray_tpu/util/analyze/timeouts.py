"""Pass 8 — timeout-budget ordering (TO): nested budgets, checked.

The PR-14 bug shape: the worker's ``task_unblocked`` RPC timeout (60s)
sat INSIDE the agent's 300s CPU re-acquire budget — on a saturated node
the agent was still legitimately waiting when the worker declared the
call dead and killed a healthy task. Nested timeouts form a contract
(the outer budget only works if every inner timeout outlasts it), but
the two constants usually live in different files and nothing relates
them — until one is edited.

The annotation makes the relation machine-checked::

    self.agent.call("task_unblocked", wid,
                    # timeout-budget: outlasts config.cpu_reacquire_budget_s
                    timeout=config.cpu_reacquire_budget_s + 30.0)

* **TO001** — the declared relation fails on defaults: the annotated
  call's ``timeout=`` value does not STRICTLY exceed the referenced
  budget (resolved against ``ray_tpu.core.config`` defaults, module
  constants and literal arithmetic). Equality counts as a violation —
  an inner timeout that expires exactly at the budget races it.
* **TO002** — the annotation can't be checked: no ``timeout``-like
  kwarg on the annotated call, an unknown ``config.<knob>``, or a
  value the resolver can't fold (dynamic expression). Declared intent
  that can't be verified is drift, same contract as GB002.

Resolvable value forms: numeric literals, ``config.<knob>`` (the
registry default), module-level ``CONST = <number>`` assignments,
``+ - * /`` arithmetic and ``max()``/``min()`` over resolvables.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)

_BUDGET_RE = re.compile(r"#\s*timeout-budget:\s*outlasts\s+(\S+)")
_TIMEOUT_KWARGS = ("timeout", "timeout_s", "deadline_s")


def _module_consts(tree: ast.Module) -> dict:
    out = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            out[stmt.targets[0].id] = stmt.value
    return out


def _config_default(knob: str) -> Optional[float]:
    from ray_tpu.core.config import _DEFS

    entry = _DEFS.get(knob)
    if entry is None:
        return None
    typ, default = entry
    if typ in (int, float):
        return float(default)
    return None


def resolve_value(expr: ast.expr, consts: dict,
                  depth: int = 0) -> Optional[float]:
    """Fold a timeout expression to a float using literals, module
    constants and config defaults; None = not statically resolvable."""
    if depth > 6:
        return None
    if isinstance(expr, ast.Constant) and isinstance(
            expr.value, (int, float)) and not isinstance(
            expr.value, bool):
        return float(expr.value)
    if isinstance(expr, ast.Name):
        bound = consts.get(expr.id)
        if bound is not None:
            return resolve_value(bound, consts, depth + 1)
        return None
    if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name) and expr.value.id == "config":
        return _config_default(expr.attr)
    if isinstance(expr, ast.BinOp):
        left = resolve_value(expr.left, consts, depth + 1)
        right = resolve_value(expr.right, consts, depth + 1)
        if left is None or right is None:
            return None
        if isinstance(expr.op, ast.Add):
            return left + right
        if isinstance(expr.op, ast.Sub):
            return left - right
        if isinstance(expr.op, ast.Mult):
            return left * right
        if isinstance(expr.op, ast.Div) and right != 0:
            return left / right
        return None
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name) \
            and expr.func.id in ("max", "min") and expr.args:
        vals = [resolve_value(a, consts, depth + 1) for a in expr.args]
        if any(v is None for v in vals):
            return None
        return max(vals) if expr.func.id == "max" else min(vals)
    return None


def _parse_budget_ref(ref: str, consts: dict) -> Optional[float]:
    """Resolve the annotation's referenced budget: a number,
    ``config.<knob>``, or a module constant name."""
    try:
        return float(ref)
    except ValueError:
        pass
    if ref.startswith("config."):
        return _config_default(ref.split(".", 1)[1])
    bound = consts.get(ref)
    if bound is not None:
        return resolve_value(bound, consts)
    return None


def _call_timeout_expr(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg in _TIMEOUT_KWARGS:
            return kw.value
    return None


def _scope_of(node: ast.AST, parents: dict) -> str:
    path: List[str] = []
    cur = node
    while cur is not None and not isinstance(cur, ast.Module):
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            path.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(path)) or "<module>"


@analysis_pass("timeout-order")
def timeout_order_pass(mod: ParsedModule) -> List:
    sink = FindingSink(mod.relpath)
    if "util/analyze/" in mod.relpath:
        # The analyzer documents its own annotation grammar — those
        # docstring examples are not declarations (same exemption the
        # contracts pass gives failpoints.py's docstring).
        return sink.findings
    annotations = {}  # line -> budget ref string
    for i, text in enumerate(mod.lines, 1):
        m = _BUDGET_RE.search(text)
        if m:
            annotations[i] = m.group(1)
    if not annotations:
        return sink.findings

    consts = _module_consts(mod.tree)
    parents: dict = {}
    for node in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    matched: set = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        lines_hit = [ln for ln in annotations
                     if node.lineno <= ln <= end and ln not in matched]
        if not lines_hit:
            continue
        timeout_expr = _call_timeout_expr(node)
        if timeout_expr is None:
            continue  # an enclosing call may still carry the kwarg
        scope = _scope_of(node, parents)
        for ln in lines_hit:
            matched.add(ln)
            ref = annotations[ln]
            outer = _parse_budget_ref(ref, consts)
            inner = resolve_value(timeout_expr, consts)
            if outer is None or inner is None:
                which = f"budget ref {ref!r}" if outer is None \
                    else "timeout value"
                sink.emit(
                    "TO002", ln, scope, ref,
                    f"# timeout-budget annotation can't be checked: "
                    f"the {which} doesn't resolve statically (config "
                    f"defaults, module constants and literal "
                    f"arithmetic are the supported forms)",
                    "reference a config.<knob> / module constant / "
                    "number, and keep the timeout= expression foldable")
            elif inner <= outer:
                sink.emit(
                    "TO001", ln, scope, f"{ref}:{inner:g}",
                    f"inner timeout {inner:g}s does not outlast the "
                    f"declared outer budget {ref} = {outer:g}s: the "
                    f"caller declares the wait dead while the budget "
                    f"it serves is still legitimately running (the "
                    f"task_unblocked-kills-healthy-task shape)",
                    f"raise the timeout above {outer:g}s (derive it "
                    f"from the budget constant so they can't drift "
                    f"apart again)")

    for ln, ref in sorted(annotations.items()):
        if ln not in matched:
            sink.emit(
                "TO002", ln, "<module>", ref,
                "# timeout-budget annotation is not attached to any "
                "call with a timeout= / timeout_s= / deadline_s= "
                "kwarg: the declared relation guards nothing",
                "put the annotation on the line range of the call "
                "whose timeout serves the budget")
    return sink.findings
