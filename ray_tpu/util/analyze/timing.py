"""Pass 12 — step-timing honesty (TH): timed walls must end at a sync.

The step-anatomy plane (round 19) stands on a discipline the runtime
cannot check: a wall-clock interval around asynchronously-dispatched
device work measures *dispatch*, not *compute*, unless a real host sync
sits between the timer reads. The bug shape is silent and flattering —
an unsynced loop reports a 40x "speedup" (the launch latency) and the
MFU gauge reads garbage. The ``# step-timed`` marker (on or directly
above a ``def``, same idiom as ``# jax-hot-path``) declares a function
whose timer reads bracket device work; this pass makes the sync
requirement static:

* **TH001** — a ``# step-timed`` function takes two or more timer
  reads (``time.perf_counter`` / ``time.monotonic`` and their ``_ns``
  forms) with no recognizable host sync between the FIRST and LAST
  read: ``jax.block_until_ready`` / ``.item()`` / ``np.asarray`` /
  ``np.array`` / ``jax.device_get`` / a builtin ``float(...)`` of a
  device value (the ``measure.py`` idiom) / a ``*sync*``-named helper
  (``_block_sync``). Whatever the interval is timing, it is not synced
  device work.
* **TH002** — a ``# step-timed`` function with fewer than two timer
  reads: the marker declares a timed region that times nothing — a
  stale annotation is a lie the next reader will trust.

Intermediate unsynced reads are fine (the anatomy host/compute split
reads the clock after dispatch *and* after the sync); the rule only
demands that a sync exists somewhere between the first and last read.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import callee_name, receiver_of

_MARK = "# step-timed"

_TIMER_READS = frozenset({
    "perf_counter", "perf_counter_ns", "monotonic", "monotonic_ns",
})
_SYNC_ATTRS = frozenset({"block_until_ready", "item", "device_get"})
_NP_ALIASES = frozenset({"np", "numpy", "onp"})


def _marked(mod: ParsedModule, fn: ast.AST) -> bool:
    for ln in (fn.lineno, fn.lineno - 1):
        if _MARK in mod.line_text(ln):
            return True
    # Decorated defs: the marker may sit above the decorator stack.
    deco = getattr(fn, "decorator_list", None)
    if deco:
        top = min(d.lineno for d in deco)
        if _MARK in mod.line_text(top - 1):
            return True
    return False


def _walk_own(fn: ast.AST):
    """Walk a function's own body, excluding nested defs (each nested
    function is its own markable region)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _is_sync(node: ast.Call) -> Optional[str]:
    """Human-readable label when the call forces device completion
    (None otherwise)."""
    name = callee_name(node) or ""
    recv = receiver_of(node)
    if name in _SYNC_ATTRS:
        return f".{name}()" if recv is not None else f"{name}()"
    if name in ("asarray", "array") and isinstance(recv, ast.Name) \
            and recv.id in _NP_ALIASES:
        return f"{recv.id}.{name}"
    if isinstance(node.func, ast.Name) and node.func.id == "float" \
            and node.args:
        return "float(...)"
    if "sync" in name.lower():
        return f"{name}()"
    return None


@analysis_pass("timing")
def timing_pass(mod: ParsedModule) -> List:
    sink = FindingSink(mod.relpath)
    model = mod.model()
    for cm, fn, scope in model.functions():
        if not _marked(mod, fn):
            continue
        reads: List[Tuple[int, int]] = []
        syncs: List[Tuple[Tuple[int, int], str]] = []
        for node in _walk_own(fn):
            if not isinstance(node, ast.Call):
                continue
            pos = (node.lineno, node.col_offset)
            if callee_name(node) in _TIMER_READS:
                reads.append(pos)
                continue
            label = _is_sync(node)
            if label is not None:
                syncs.append((pos, label))
        if len(reads) < 2:
            sink.emit(
                "TH002", fn.lineno, scope, "untimed",
                f"`# step-timed` region {scope} takes "
                f"{len(reads)} timer read(s): the marker declares a "
                f"timed step region but the function times nothing — "
                f"a stale annotation the next reader will trust",
                "remove the marker, or time the region (two "
                "perf_counter reads bracketing the work)")
            continue
        first, last = min(reads), max(reads)
        if not any(first < pos <= last for pos, _ in syncs):
            sink.emit(
                "TH001", last[0], scope, "unsynced-wall",
                f"`# step-timed` region {scope} measures a wall "
                f"between timer reads (lines {first[0]}-{last[0]}) "
                f"with no host sync between them: around async "
                f"dispatch this times the launch, not the device — "
                f"the MFU/anatomy numbers built on it are fiction",
                "force completion before the closing read "
                "(jax.block_until_ready on the step outputs, or "
                "float() a device scalar)")
    return sink.findings
