"""Pass 3 — finalizer-safety: the PR-5 GC-deadlock class, as a rule.

``__del__`` methods and ``weakref.finalize`` callbacks run from the
garbage collector, which can fire on *whatever thread happens to be
allocating* — including one already inside a critical section of the
very lock the finalizer wants. PR 5 hit exactly this: ObjectRef
finalizers calling ``_decref`` self-deadlocked the local backend when a
GC pass fired inside ``_entry`` (building a ``threading.Event`` while
holding the then non-reentrant ``_objects_lock``); the whole backend
wedged behind one thread. Reproduced 3/3, diagnosed via faulthandler —
now a static rule instead of a war story.

Rules (checked over code reachable from a finalizer root through
intra-class ``self.`` calls and module-level calls, three levels deep):

* **FS001** — a non-reentrant ``threading.Lock`` acquired in
  finalizer-reachable code: must be RLock-protocol, because the GC can
  re-enter while the allocating thread holds it.
* **FS002** — an RPC call (``.call`` / ``.call_stream``) in
  finalizer-reachable code: a finalizer blocking on the network turns
  any allocation into a potential multi-second stall (and a deadlock
  when the RPC needs a lock the interrupted thread holds).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.util.analyze.core import (
    Finding,
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import (
    FunctionContext,
    callee_name,
    iter_events,
    receiver_of,
)

_MAX_DEPTH = 3


def _finalize_callback(call: ast.Call) -> Optional[ast.expr]:
    """The callback expr of a ``weakref.finalize(obj, cb, ...)`` call."""
    name = callee_name(call)
    if name != "finalize":
        return None
    recv = receiver_of(call)
    if recv is not None and not (isinstance(recv, ast.Name)
                                 and recv.id == "weakref"):
        return None
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _callees(fn: ast.AST, module_funcs: Set[str]) -> Tuple[Set[str],
                                                           Set[str]]:
    """(self-method names, module-level function names) this function
    calls anywhere in its body (nested defs included — a closure
    defined in finalizer-reachable code may run there too)."""
    self_calls: Set[str] = set()
    mod_calls: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Name) and f.value.id == "self":
            self_calls.add(f.attr)
        elif isinstance(f, ast.Name) and f.id in module_funcs:
            mod_calls.add(f.id)
    return self_calls, mod_calls


@analysis_pass("finalizer")
def finalizer_pass(mod: ParsedModule) -> List[Finding]:
    model = mod.model()
    funcs = model.functions()
    # Index: (class name | "", function leaf name) -> (cm, fn, scope).
    index: Dict[Tuple[str, str], tuple] = {}
    module_funcs: Set[str] = set()
    for cm, fn, scope in funcs:
        owner = cm.name if cm is not None else ""
        index.setdefault((owner, fn.name), (cm, fn, scope))
        if cm is None and "." not in scope:
            module_funcs.add(fn.name)

    # Roots: __del__ methods + weakref.finalize callbacks. root_key is
    # the stable per-root identity findings carry in their baseline key
    # (two finalize callbacks in one class must never share a key).
    roots: List[Tuple[str, str, str, str]] = []  # (owner, name, key, desc)
    for cm, fn, scope in funcs:
        if fn.name == "__del__" and cm is not None:
            roots.append((cm.name, "__del__", f"{cm.name}.__del__",
                          f"{cm.name}.__del__"))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            cb = _finalize_callback(node)
            if cb is None:
                continue
            if (isinstance(cb, ast.Attribute)
                    and isinstance(cb.value, ast.Name)
                    and cb.value.id == "self" and cm is not None):
                roots.append((cm.name, cb.attr,
                              f"finalize.{cm.name}.{cb.attr}",
                              f"weakref.finalize -> {cm.name}.{cb.attr}"
                              f" ({mod.relpath}:{node.lineno})"))
            elif isinstance(cb, ast.Name):
                if cb.id in module_funcs:
                    roots.append(("", cb.id, f"finalize.{cb.id}",
                                  f"weakref.finalize -> {cb.id} "
                                  f"({mod.relpath}:{node.lineno})"))

    sink = FindingSink(mod.relpath)
    emit = sink.emit

    for owner, name, root_key, root_desc in roots:
        # BFS through the call graph, bounded depth.
        seen: Set[Tuple[str, str]] = set()
        frontier = [(owner, name, 0)]
        while frontier:
            cur_owner, cur_name, depth = frontier.pop()
            if (cur_owner, cur_name) in seen or depth > _MAX_DEPTH:
                continue
            seen.add((cur_owner, cur_name))
            entry = index.get((cur_owner, cur_name))
            if entry is None:
                continue
            cm, fn, scope = entry
            ctx = FunctionContext(model, cm)
            for ev in iter_events(fn, ctx):
                if ev.kind == "acquire" \
                        and ev.data.info.reentrant is False:
                    emit("FS001", ev.node.lineno, scope,
                         f"{ev.data.name}:{root_key}",
                         f"non-reentrant lock {ev.data.qualname} "
                         f"acquired in code reachable from finalizer "
                         f"{root_desc}: a GC pass can fire the "
                         f"finalizer on a thread already holding it — "
                         f"the PR-5 self-deadlock",
                         "make the lock RLock-protocol (threading.RLock "
                         "or equivalent) or move the finalizer's work "
                         "onto a queue drained outside GC")
                elif ev.kind == "blocking" and ev.data[0] == "rpc":
                    emit("FS002", ev.node.lineno, scope,
                         f"rpc:{root_key}",
                         f"RPC call in code reachable from finalizer "
                         f"{root_desc}: finalizers run from GC on "
                         f"arbitrary threads and must never block on "
                         f"the network",
                         "enqueue the work for a background flusher "
                         "instead of calling out of the finalizer")
            sc, mc = _callees(fn, module_funcs)
            for callee in sc:
                if cur_owner:
                    frontier.append((cur_owner, callee, depth + 1))
            for callee in mc:
                frontier.append(("", callee, depth + 1))
    return sink.findings
