"""Pass 2 — blocking-under-lock; pass 4 — await/blocking in async defs.

The TPU-concurrency-limits observation applies verbatim to the head:
host-side serialization is what caps pod-scale throughput, so an RPC or
sqlite commit inside a shard lock's critical section is a *performance*
bug even before it's a hang risk (the exact shape PR-6 spent a round
unwinding).

Blocking rules (fire only while a resolved lock is held; a lock whose
declaration carries ``# analyze: allow-blocking`` — a dedicated I/O
mutex like the persistent store's sqlite connection lock — is exempt):

* **BL001** — RPC (``.call`` / ``.call_stream``) under a lock.
* **BL002** — ``time.sleep`` under a lock.
* **BL003** — ``Thread.join`` / ``Future.result`` under a lock.
* **BL004** — ``Event.wait`` (or a Condition wait that does NOT release
  the held lock) under a lock.
* **BL005** — sqlite/db ``commit`` under a lock.

Async rules (inside ``async def`` — the serve/router path bug class:
a sync lock held across a suspension point blocks every other coroutine
on the loop AND every thread contending the lock):

* **AH001** — ``await`` while a sync ``threading`` lock is held.
* **AH002** — a known blocking call while a sync lock is held in a
  coroutine (double trouble: stalls the loop and the lock).
"""

from __future__ import annotations

from typing import List

from ray_tpu.util.analyze.core import (
    Finding,
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import FunctionContext, iter_events

import ast

_BLOCK_RULE = {
    "rpc": ("BL001", "an RPC round-trip"),
    "sleep": ("BL002", "a sleep"),
    "join": ("BL003", "a thread join"),
    "future": ("BL003", "a future result wait"),
    "wait": ("BL004", "an event/condition wait"),
    "sqlite": ("BL005", "a sqlite commit"),
}


def _effective_held(held):
    """Locks the finding charges: allow-blocking locks are exempt."""
    return [h for h in held if not h.info.allow_blocking]


@analysis_pass("blocking")
def blocking_pass(mod: ParsedModule) -> List[Finding]:
    model = mod.model()
    sink = FindingSink(mod.relpath)
    emit = sink.emit

    for cm, fn, scope in model.functions():
        if isinstance(fn, ast.AsyncFunctionDef):
            continue  # pass 4's jurisdiction
        ctx = FunctionContext(model, cm)
        for ev in iter_events(fn, ctx):
            if ev.kind == "blocking":
                held = _effective_held(ev.held)
                if not held:
                    continue
                kind, detail = ev.data
                rule, what = _BLOCK_RULE[kind]
                lock = held[-1]
                emit(rule, ev.node.lineno, scope,
                     f"{kind}:{lock.name}",
                     f"{what} ({detail}) inside the critical section "
                     f"of {lock.qualname}: every thread contending "
                     f"this lock serializes behind the wait",
                     "move the blocking work outside the lock (snapshot "
                     "under the lock, act after release), or mark a "
                     "dedicated I/O mutex with "
                     "`# analyze: allow-blocking`")
            elif ev.kind == "self_call" and ev.held and cm is not None:
                held = _effective_held(ev.held)
                if not held:
                    continue
                summary = model.summaries_for(cm).get(ev.data)
                if summary is None:
                    continue
                lock = held[-1]
                for kind, detail, hline in summary.blocking:
                    rule, what = _BLOCK_RULE[kind]
                    emit(rule, ev.node.lineno, scope,
                         f"{kind}:{lock.name}:via:{ev.data}",
                         f"{what} ({detail}, inside self.{ev.data}() "
                         f"at line {hline}) runs under {lock.qualname} "
                         f"held here",
                         "hoist the helper call out of the critical "
                         "section or split the helper")
    return sink.findings


@analysis_pass("async-lock")
def async_lock_pass(mod: ParsedModule) -> List[Finding]:
    model = mod.model()
    sink = FindingSink(mod.relpath)
    emit = sink.emit

    for cm, fn, scope in model.functions():
        if not isinstance(fn, ast.AsyncFunctionDef):
            continue
        ctx = FunctionContext(model, cm)
        for ev in iter_events(fn, ctx):
            held = _effective_held(ev.held)
            if not held:
                continue
            lock = held[-1]
            if ev.kind == "await":
                emit("AH001", ev.node.lineno, scope,
                     f"await:{lock.name}",
                     f"await while holding sync lock {lock.qualname}: "
                     f"the coroutine suspends with the lock held — "
                     f"every thread AND every other coroutine touching "
                     f"it stalls (the PR-8 span-restore bug class)",
                     "release the lock before awaiting (snapshot state "
                     "under it), or use an asyncio.Lock for "
                     "loop-internal state")
            elif ev.kind == "blocking":
                kind, detail = ev.data
                emit("AH002", ev.node.lineno, scope,
                     f"{kind}:{lock.name}",
                     f"blocking call ({detail}) while holding "
                     f"{lock.qualname} inside a coroutine: stalls the "
                     f"event loop and the lock at once",
                     "run the blocking work in an executor after "
                     "releasing the lock")
    return sink.findings
