"""Pass 6 — retry/idempotence contracts (RT): the PR-11..13 class.

Every subsystem shipped since the PG 2PC has needed a review round to
catch the same bug: an RPC retried on connection loss whose handler
was never built to absorb a replay. A severed reply is AMBIGUOUS — the
peer may have executed the request (``maybe_executed=True`` on the
``ConnectionLost``), so a blind resubmit forks the effect: a bundle
reserved twice, a stream admitted twice holding two decode slots, a
metrics batch double-counted. The declared contract this pass checks:

* **RT001** — a *retried* RPC call site (``.call("<method>", ...)``
  re-executed by a retry loop whose exception handler swallows the
  failure) must either target a handler declared ``# idempotent`` on
  its ``def rpc_<method>`` line, or the retry construct must consult
  ``maybe_executed`` to separate ambiguous losses from safe ones.
  Fan-out loops (the call references the loop variable — a different
  target per iteration) are not retries and are exempt.
* **RT002** — a handler declared ``# idempotent`` must actually show a
  replay-absorb pattern: a membership test (``key in table`` early-ack
  — the 2PC prepare shape), keyed last-write-wins stores, or
  dedup helpers. A declared-idempotent handler that appends/increments
  without any keying executes twice on replay — the declaration lies.
* **RT003** — a resubmit-style retry loop (``for attempt in
  range(n)`` around ``call_stream`` / a ``*submit*`` call) must narrow
  the exceptions it retries: catching ``Exception`` retries
  ``GetTimeoutError`` too, and a timed-out submit MAY have executed on
  a wedged replica (the exact PR-13 blind-resubmit bug — a second
  admission orphans a slot-holding stream). Handlers that re-``raise``
  or ``break`` are not retries.

The idempotent-handler table is built from ``# idempotent`` markers on
``def rpc_*`` lines across the repo tree (cached) plus the module under
analysis (so fixtures are self-contained), the same
declared-intent-then-checked workflow as ``# guarded-by``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import callee_name, receiver_of

_IDEMPOTENT_DEF_RE = re.compile(
    r"def\s+(rpc_)?(\w+)\s*\(.*#\s*idempotent\b")

# Mutators that ABSORB a replay by construction (keyed overwrite /
# explicit dedup) vs ones that compound per delivery.
_ABSORB_CALLS = frozenset({"setdefault", "discard"})
_COMPOUND_CALLS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "inc",
    "push", "heappush", "put", "put_nowait",
})

_BROAD_EXCEPTS = frozenset({"Exception", "BaseException"})
_RETRYABLE_EXCEPTS = _BROAD_EXCEPTS | frozenset({
    "ConnectionLost", "OSError", "IOError", "RpcError", "RuntimeError",
    "TimeoutError", "GetTimeoutError", "ActorError", "ConnectionError",
})

_repo_idempotent_cache: Optional[frozenset] = None


_DEF_NAME_RE = re.compile(r"^\s*def\s+(rpc_)?(\w+)\s*\(")


def _declared_idempotent(lines: List[str]) -> Set[str]:
    """Handler METHOD names (``rpc_`` prefix stripped — the wire name a
    ``.call()`` uses) declared ``# idempotent`` in this source: the
    marker sits on the def line itself, or on its own line directly
    above the def (both forms are honored by RT001 and RT002 alike)."""
    out: Set[str] = set()
    for i, text in enumerate(lines):
        m = _IDEMPOTENT_DEF_RE.search(text)
        if m:
            out.add(m.group(2))
            continue
        if text.strip().startswith("# idempotent") \
                and i + 1 < len(lines):
            d = _DEF_NAME_RE.match(lines[i + 1])
            if d:
                out.add(d.group(2))
    return out


def repo_idempotent_table() -> frozenset:
    """``# idempotent``-declared handler names across the package tree
    (cached: the table changes only when source changes, and the
    analyzer process is one run)."""
    global _repo_idempotent_cache
    if _repo_idempotent_cache is None:
        from ray_tpu.util.analyze.core import default_paths

        out: Set[str] = set()
        for path in default_paths():
            try:
                with open(path, encoding="utf-8") as f:
                    out |= _declared_idempotent(f.read().splitlines())
            except OSError:
                continue
        _repo_idempotent_cache = frozenset(out)
    return _repo_idempotent_cache


def _rpc_method_literal(call: ast.Call) -> Optional[str]:
    """The method-name literal of an ``x.call("m", ...)`` /
    ``x.call_stream("m", ...)`` RPC (None = not that shape)."""
    if callee_name(call) not in ("call", "call_stream"):
        return None
    if receiver_of(call) is None:
        return None
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _loop_targets(loop: ast.AST) -> Set[str]:
    if isinstance(loop, ast.For):
        return _names_in(loop.target)
    return set()


def _always_exits(stmts: List[ast.stmt], break_exits: bool) -> bool:
    """Every control path through these statements leaves the loop
    under evaluation (raise / return — and ``break`` only when the
    loop it breaks IS that loop). A conditional exit still falls
    through on the other branch — that path retries."""
    for stmt in stmts:
        if isinstance(stmt, (ast.Raise, ast.Return)):
            return True
        if isinstance(stmt, ast.Break) and break_exits:
            return True
        if isinstance(stmt, ast.If):
            if stmt.orelse and _always_exits(stmt.body, break_exits) \
                    and _always_exits(stmt.orelse, break_exits):
                return True
        if isinstance(stmt, ast.Try):
            if _always_exits(stmt.finalbody, break_exits):
                return True
    return False


def _handler_retries(handler: ast.ExceptHandler,
                     break_exits: bool = True) -> bool:
    """A handler RETRIES the loop under evaluation when at least one
    control path through it re-enters the iteration: ``continue`` or
    plain fall-through. ``if attempt == 2: return False`` exits only
    the LAST attempt — the earlier ones retry, which is what matters
    for a blind-resubmit check. A ``break`` inside a nested fan-out
    loop doesn't exit an OUTER retry loop (``break_exits=False``):
    the 2PC prepare round aborts its fan-out, rolls back and re-runs
    — every prepared node sees a replay."""
    return not _always_exits(handler.body, break_exits)


def _handler_types(handler: ast.ExceptHandler) -> Set[str]:
    """Exception class names a handler catches ('' = bare except)."""
    t = handler.type
    if t is None:
        return {""}
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out: Set[str] = set()
    for e in elts:
        if isinstance(e, ast.Name):
            out.add(e.id)
        elif isinstance(e, ast.Attribute):
            out.add(e.attr)
    return out


def _is_bounded_retry_loop(loop: ast.AST) -> bool:
    """``for <v> in range(...)`` — the bounded-resubmit idiom."""
    return (isinstance(loop, ast.For)
            and isinstance(loop.iter, ast.Call)
            and callee_name(loop.iter) == "range")


def _mentions_maybe_executed(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr == "maybe_executed":
            return True
        if isinstance(n, ast.Constant) and n.value == "maybe_executed":
            return True  # getattr(e, "maybe_executed", False)
    return False


def _scope_of(fn_stack: List[str]) -> str:
    return ".".join(fn_stack) or "<module>"


class _RetryWalker(ast.NodeVisitor):
    """Find (loop, try, handler, rpc-call) retry constructs: an RPC
    call is *retried* by loop L when some enclosing ``try`` INSIDE L
    catches its failure with a handler that re-enters the iteration.
    A try outside the loop (or a handler that raises/returns/breaks)
    lets the failure escape — no retry, no finding."""

    def __init__(self, mod: ParsedModule, sink: FindingSink,
                 idempotent: frozenset):
        self.mod = mod
        self.sink = sink
        self.idempotent = idempotent
        self.scope_stack: List[str] = []
        # (loop node, loop target names)
        self.loop_stack: List[Tuple[ast.AST, Set[str]]] = []
        # (loop depth at try entry, retrying handlers)
        self.try_stack: List[Tuple[int, List[ast.ExceptHandler]]] = []

    # -- scope bookkeeping -------------------------------------------------

    def _walk_scoped(self, node, name: str):
        self.scope_stack.append(name)
        saved = (self.loop_stack, self.try_stack)
        self.loop_stack, self.try_stack = [], []
        self.generic_visit(node)
        self.loop_stack, self.try_stack = saved
        self.scope_stack.pop()

    def visit_FunctionDef(self, node):
        self._walk_scoped(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._walk_scoped(node, node.name)

    def visit_Lambda(self, node):
        pass

    # -- retry-construct detection ----------------------------------------

    def _enter_loop(self, node):
        self.loop_stack.append((node, _loop_targets(node)))
        self.generic_visit(node)
        self.loop_stack.pop()

    visit_For = _enter_loop
    visit_While = _enter_loop

    def visit_Try(self, node: ast.Try):
        self.try_stack.append((len(self.loop_stack),
                               list(node.handlers)))
        for stmt in node.body:
            self.visit(stmt)
        self.try_stack.pop()
        # Handler / else / finally bodies are not guarded by this try.
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        for stmt in node.orelse + node.finalbody:
            self.visit(stmt)

    def visit_Call(self, node: ast.Call):
        self.generic_visit(node)
        method = _rpc_method_literal(node)
        if method is None or not self.loop_stack:
            return
        scope = _scope_of(self.scope_stack)
        # Innermost loop OUT: the first loop that retries this call
        # (via a try inside it) and isn't a fan-out over it decides.
        for depth in range(len(self.loop_stack), 0, -1):
            loop, targets = self.loop_stack[depth - 1]
            retrying = [
                h for d, hs in self.try_stack if d >= depth
                for h in hs
                if _handler_retries(h, break_exits=(d == depth))
                and _handler_types(h) & (_RETRYABLE_EXCEPTS | {""})]
            if not retrying:
                continue  # failures escape this loop — check outer
            # Fan-out exemption: the call varies with the loop variable
            # (a different peer per iteration) — nothing is re-sent.
            if targets and (_names_in(node) & targets):
                continue
            guarded = _mentions_maybe_executed(loop)
            if method not in self.idempotent and not guarded:
                self.sink.emit(
                    "RT001", node.lineno, scope, method,
                    f"RPC {method!r} is retried by this loop (a "
                    f"swallowing except handler re-enters the "
                    f"iteration) but the handler is not declared "
                    f"`# idempotent` and the retry never consults "
                    f"maybe_executed: a lost REPLY resubmits a request "
                    f"the peer may already have executed",
                    "declare the handler idempotent (and make it "
                    "absorb replays), or branch on maybe_executed "
                    "before resubmitting")
            # RT003: resubmit-style bounded retries must narrow what
            # they retry — a broad catch retries timeouts, and a
            # timed-out submit may have executed.
            if _is_bounded_retry_loop(loop) and (
                    callee_name(node) == "call_stream"
                    or "submit" in method):
                broad = [h for h in retrying
                         if _handler_types(h) & (_BROAD_EXCEPTS
                                                 | {""})]
                if broad and not guarded:
                    self.sink.emit(
                        "RT003", node.lineno, scope, method,
                        f"bounded resubmit of {method!r} retries on a "
                        f"broad exception catch: a timeout/wedged-peer "
                        f"failure MAY have executed the submit, and "
                        f"the blind resubmit double-admits (the PR-13 "
                        f"orphaned-decode-slot shape)",
                        "narrow the retried exceptions to dead-peer "
                        "cases (ActorError / empty-table) and re-raise "
                        "ambiguous ones (GetTimeoutError)")
            break


def _absorbs_replay(fn: ast.AST) -> bool:
    """A replay-absorb pattern is visible: membership test, keyed
    overwrite, or dedup helper."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
            return True
        if isinstance(node, ast.Call) and \
                callee_name(node) in _ABSORB_CALLS:
            return True
        if isinstance(node, ast.Call) and "duplicate" in \
                callee_name(node).lower():
            return True
    return False


def _compounds_state(fn: ast.AST) -> Optional[int]:
    """Line of the first mutation that COMPOUNDS per delivery (append /
    +=-style), or None. Keyed subscript stores are last-write-wins and
    don't count."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            continue
        if isinstance(node, ast.AugAssign):
            return node.lineno
        if isinstance(node, ast.Call) and \
                callee_name(node) in _COMPOUND_CALLS:
            return node.lineno
    return None


@analysis_pass("retry")
def retry_pass(mod: ParsedModule) -> List:
    sink = FindingSink(mod.relpath)
    local = _declared_idempotent(mod.lines)
    # Skip the repo sweep for out-of-tree fixtures rooted elsewhere —
    # relpath escaping the package means a test tmpdir.
    table = frozenset(local) | (
        repo_idempotent_table()
        if not mod.relpath.startswith("..") else frozenset())
    _RetryWalker(mod, sink, table).visit(mod.tree)

    # RT002 — declared-idempotent handlers must absorb replays.
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        text = mod.line_text(node.lineno)
        # The marker may sit on the def line or the line above it.
        above = mod.line_text(node.lineno - 1).strip()
        marked = "# idempotent" in text or above.startswith(
            "# idempotent")
        if not marked:
            continue
        compound_line = _compounds_state(node)
        if compound_line is not None and not _absorbs_replay(node):
            sink.emit(
                "RT002", compound_line, node.name, node.name,
                f"handler {node.name} is declared `# idempotent` but "
                f"compounds state per delivery (append/+= at line "
                f"{compound_line}) with no visible replay-absorb "
                f"pattern (membership early-ack, keyed overwrite, "
                f"dedup helper): a replayed request executes twice",
                "absorb replays (check a key before acting, or key the "
                "write) — or drop the declaration and guard callers "
                "with maybe_executed")
    return sink.findings
