"""Pass 1 — lock-order: acquisition order, re-entry, declared intent.

Rules:

* **LO001** — lock-order inversion against the module's declared
  ``LOCK_ORDER`` tuple (head.py commits ``("_lock", "_obj_lock",
  "_event_lock")``): acquiring an earlier-ranked lock while holding a
  later-ranked one is the deadlock shape the round-6 shard split could
  only document in prose.
* **LO002** — same-lock re-entry where the lock is a non-reentrant
  ``threading.Lock`` (directly nested ``with``, through a Condition
  alias, or via a helper called one level deep under the lock).
* **LO003** — inconsistent discovered order: the same two locks are
  nested in both directions somewhere in the module (a latent ABBA
  deadlock even when no order was declared for them).
* **LO004** — ``LOCK_ORDER`` drift: the declared tuple names a lock no
  class in the module defines (the machine-readable order and the code
  have diverged).
* **GB001** — a ``# guarded-by: <lock>`` annotated attribute is
  mutated without its declared lock held (init-time writes exempt;
  private helpers whose every intra-class call site holds the lock are
  treated as guarded by their callers).
* **GB002** — a ``# guarded-by:`` annotation names a lock the class
  does not define (declared intent that can't be checked is drift).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ray_tpu.util.analyze.core import (
    Finding,
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import (
    ClassModel,
    FunctionContext,
    ModuleModel,
    iter_events,
)


def _is_private_helper(name: str) -> bool:
    return name.startswith("_") and not name.startswith("__")


@analysis_pass("lock-order")
def lock_order_pass(mod: ParsedModule) -> List[Finding]:
    model = mod.model()
    sink = FindingSink(mod.relpath)
    emit = sink.emit
    order_idx = {n: i for i, n in enumerate(model.lock_order)}

    if model.lock_order:
        defined = set()
        for cls in model.classes.values():
            defined |= set(cls.locks)
        for name in model.lock_order:
            if name not in defined:
                emit("LO004", 1, "<module>", name,
                     f"LOCK_ORDER names {name!r} but no class in this "
                     f"module defines that lock — the declared order "
                     f"and the code have drifted",
                     "update LOCK_ORDER to match the live shard locks")

    # Aggregated per lock-owner: (outer, inner) -> first (line, scope).
    edges: Dict[str, Dict[Tuple[str, str], Tuple[int, str]]] = {}

    def note_nesting(owner: str, outer, inner, line, scope, via=""):
        suffix = f" (via {via})" if via else ""
        if outer.qualname == inner.qualname:
            if inner.info.reentrant is False:
                emit("LO002", line, scope, inner.name,
                     f"re-entry on non-reentrant lock "
                     f"{inner.qualname}{suffix}: this thread already "
                     f"holds it — threading.Lock self-deadlocks",
                     "make the lock an RLock (or restructure so the "
                     "critical sections don't nest)")
            return
        edges.setdefault(owner, {}).setdefault(
            (outer.name, inner.name), (line, scope))
        oi = order_idx.get(outer.name)
        ii = order_idx.get(inner.name)
        if oi is not None and ii is not None and oi > ii:
            emit("LO001", line, scope, f"{outer.name}->{inner.name}",
                 f"lock-order inversion: acquiring {inner.qualname} "
                 f"while holding {outer.qualname}{suffix} inverts the "
                 f"declared LOCK_ORDER "
                 f"({' -> '.join(model.lock_order)})",
                 "acquire the locks in declared order, or hoist the "
                 "earlier lock's work out of the later lock's critical "
                 "section")

    for cm, fn, scope in model.functions():
        ctx = FunctionContext(model, cm)
        owner = cm.name if cm is not None else "<module>"
        for ev in iter_events(fn, ctx):
            if ev.kind == "acquire":
                for h in ev.held:
                    note_nesting(owner, h, ev.data, ev.node.lineno,
                                 scope)
            elif ev.kind == "self_call" and ev.held and cm is not None:
                summary = model.summaries_for(cm).get(ev.data)
                if summary is None:
                    continue
                for inner, _hline in summary.acquires:
                    for h in ev.held:
                        note_nesting(owner, h, inner, ev.node.lineno,
                                     scope, via=f"self.{ev.data}()")

    for owner, table in sorted(edges.items()):
        for (a, b), (line, scope) in sorted(table.items()):
            if (b, a) in table and a < b \
                    and not (a in order_idx and b in order_idx):
                other_line, other_scope = table[(b, a)]
                emit("LO003", line, scope, f"{a}<->{b}",
                     f"inconsistent lock order in {owner}: {a} -> {b} "
                     f"here but {b} -> {a} at {mod.relpath}:"
                     f"{other_line} ({other_scope}) — a latent ABBA "
                     f"deadlock",
                     "pick one order for the pair and add it to "
                     "LOCK_ORDER so the analyzer enforces it")

    for cls in model.classes.values():
        sink.findings.extend(_guarded_by_findings(mod, model, cls))
    return sink.findings


def _guaranteed_held(cls: ClassModel,
                     call_sites: Dict[str, List[Tuple[str, frozenset]]],
                     closure_leafs: frozenset = frozenset()
                     ) -> Dict[str, frozenset]:
    """Locks every execution of a private helper provably runs under:
    the meet over its intra-class call sites of (locks held at the
    site) ∪ (locks the CALLER is itself guaranteed) — a small fixpoint
    so ``rpc_schedule_batch -> _schedule_locked -> _pick`` chains carry
    the lock two levels down. Self-recursive sites are skipped (the
    recursive call inherits whatever the outer call proved). Public
    methods are entry points: nothing is guaranteed for them; closures
    (any name) qualify — their only callers are in this class by
    construction, and one passed solely as a Thread target has no call
    sites, so nothing is guaranteed and its body must lock for
    itself."""
    universe = frozenset(cls.locks)
    guaranteed: Dict[str, frozenset] = {}
    for name in call_sites:
        if name in closure_leafs or (
                _is_private_helper(name) and name in cls.methods):
            guaranteed[name] = universe
    for _ in range(10):
        changed = False
        for name in guaranteed:
            sites = [(c, held) for c, held in call_sites[name]
                     if c != name]
            if not sites:
                new = frozenset()
            else:
                new = universe
                for caller, held in sites:
                    new &= held | guaranteed.get(caller, frozenset())
            if new != guaranteed[name]:
                guaranteed[name] = new
                changed = True
        if not changed:
            break
    return guaranteed


def _guarded_by_findings(mod: ParsedModule, model: ModuleModel,
                         cls: ClassModel) -> List[Finding]:
    findings: List[Finding] = []
    if not cls.guarded_by:
        return findings
    for attr, lockname in sorted(cls.guarded_by.items()):
        if lockname not in cls.locks \
                and lockname not in model.module_locks:
            findings.append(Finding(
                "GB002", mod.relpath,
                cls.node.lineno, cls.name, attr,
                f"# guarded-by: {lockname} on {cls.name}.{attr} names "
                f"a lock this class does not define",
                "annotate with the real lock attribute name"))

    # method/closure name -> (caller leaf, held-lock names) at every
    # intra-class call site (callers-hold-the-lock inference). Bare
    # local_call names count too: a closure defined AND invoked inside
    # a critical section is guarded by its call site, not its own body.
    call_sites: Dict[str, List[Tuple[str, frozenset]]] = {}
    mutations: List[Tuple[str, str, ast.AST, set]] = []
    closure_leafs: set = set()
    for cm, fn, scope in model.functions():
        if cm is None or cm.name != cls.name:
            continue
        if fn.name not in cls.methods:
            closure_leafs.add(fn.name)
        ctx = FunctionContext(model, cm)
        caller_leaf = scope.rsplit(".", 1)[-1]
        for ev in iter_events(fn, ctx):
            held_names = {h.name for h in ev.held}
            if ev.kind in ("self_call", "local_call"):
                call_sites.setdefault(ev.data, []).append(
                    (caller_leaf, frozenset(held_names)))
            elif ev.kind == "mutate" and ev.data in cls.guarded_by:
                mutations.append((scope, ev.data, ev.node, held_names))

    guaranteed = _guaranteed_held(cls, call_sites, closure_leafs)

    emitted: set = set()
    for scope, attr, node, held_names in mutations:
        leaf = scope.rsplit(".", 1)[-1]
        if leaf == "__init__":
            continue
        lockname = cls.guarded_by[attr]
        if lockname not in cls.locks \
                and lockname not in model.module_locks:
            continue  # GB002 already reported
        if lockname in held_names:
            continue
        if lockname in guaranteed.get(leaf, frozenset()):
            continue  # every (transitive) caller holds the lock
        ident = (scope, attr, node.lineno)
        if ident in emitted:
            continue
        emitted.add(ident)
        findings.append(Finding(
            "GB001", mod.relpath, node.lineno, scope, attr,
            f"{cls.name}.{attr} is declared guarded-by {lockname} but "
            f"is mutated here without it held",
            f"take `with self.{lockname}:` around the mutation (or fix "
            f"the guarded-by annotation if intent changed)"))
    return findings
