"""Analyzer core: findings, pass registry, runner, baseline, diff mode.

Every pass is a function ``(module: ParsedModule) -> Iterable[Finding]``
registered under a rule-family name via :func:`analysis_pass`. The
runner parses each target file once (stdlib ``ast`` — no new deps) and
hands the same :class:`ParsedModule` to every selected pass.

Findings carry a *stable key* (rule : relpath : scope : detail — no
line numbers, so unrelated edits don't churn the allowlist) matched
against the committed ``ANALYZE_BASELINE.json``: only findings whose
key is absent from the baseline fail the run. Baseline entries map the
key to a one-line justification; an entry whose key no longer matches
any finding is reported as stale so the allowlist can only shrink.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
from typing import Callable, Dict, Iterable, List, Optional, Sequence

BASELINE_FILENAME = "ANALYZE_BASELINE.json"

# Line pragma: `# analyze: ignore[LO001]` or `# analyze: ignore` —
# suppresses findings anchored on that source line.
_IGNORE_RE = re.compile(r"#\s*analyze:\s*ignore(?:\[([A-Z0-9, ]+)\])?")


class Finding:
    """One rule violation at a source location."""

    __slots__ = ("rule", "path", "line", "scope", "detail", "message",
                 "hint")

    def __init__(self, rule: str, path: str, line: int, scope: str,
                 detail: str, message: str, hint: str = ""):
        self.rule = rule
        self.path = path  # repo-relative
        self.line = line
        self.scope = scope  # enclosing class.method (or <module>)
        self.detail = detail  # rule-specific stable discriminator
        self.message = message
        self.hint = hint

    @property
    def key(self) -> str:
        """Baseline identity: stable across line-number churn."""
        return f"{self.rule}:{self.path}:{self.scope}:{self.detail}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Finding {self.rule} {self.path}:{self.line} {self.detail}>"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "file": self.path, "line": self.line,
            "scope": self.scope, "detail": self.detail, "key": self.key,
            "message": self.message, "hint": self.hint,
        }

    def format(self) -> str:
        out = (f"{self.path}:{self.line}: {self.rule} [{self.scope}] "
               f"{self.message}")
        if self.hint:
            out += f"\n    fix: {self.hint}"
        return out


class ParsedModule:
    """One target file, parsed once and shared by every pass."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._ignores: Optional[Dict[int, Optional[set]]] = None
        self._model = None

    def model(self):
        """The module's lock/alias model, built once and shared by
        every pass (the resolver walk is the expensive part)."""
        if self._model is None:
            from ray_tpu.util.analyze.resolver import ModuleModel

            self._model = ModuleModel(self.tree, self.lines)
        return self._model

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def ignored(self, rule: str, lineno: int) -> bool:
        """True when the line carries `# analyze: ignore[...]` for this
        rule (or a bare ignore covering every rule)."""
        if self._ignores is None:
            table: Dict[int, Optional[set]] = {}
            for i, text in enumerate(self.lines, 1):
                m = _IGNORE_RE.search(text)
                if m:
                    rules = m.group(1)
                    table[i] = (set(r.strip() for r in rules.split(","))
                                if rules else None)
            self._ignores = table
        rules = self._ignores.get(lineno, False)
        if rules is False:
            return False
        return rules is None or rule in rules


class FindingSink:
    """Deduping finding collector shared by the passes: one emit
    helper, one identity rule (rule, line, scope, detail)."""

    def __init__(self, relpath: str):
        self.relpath = relpath
        self.findings: List[Finding] = []
        self._seen: set = set()

    def emit(self, rule: str, line: int, scope: str, detail: str,
             message: str, hint: str = "") -> None:
        ident = (rule, line, scope, detail)
        if ident in self._seen:
            return
        self._seen.add(ident)
        self.findings.append(Finding(rule, self.relpath, line, scope,
                                     detail, message, hint))


# rule-family name -> pass callable
PASSES: "Dict[str, Callable[[ParsedModule], Iterable[Finding]]]" = {}

# rule-family name -> cross-module checker run only on FULL scans (the
# whole tree must be in view: stale failpoint sites, gauge families
# emitted in one module and retracted in another). Keyed by the same
# family name as the per-module pass so --rule selection covers both.
CROSS_PASSES: "Dict[str, Callable[[Sequence[ParsedModule]], Iterable[Finding]]]" = {}


def analysis_pass(name: str):
    """Register a pass under a ``--rule`` family name."""

    def deco(fn):
        PASSES[name] = fn
        return fn

    return deco


def cross_pass(name: str):
    """Register a full-scan cross-module checker for a rule family."""

    def deco(fn):
        CROSS_PASSES[name] = fn
        return fn

    return deco


def repo_root() -> str:
    """The checkout root (this file lives in ray_tpu/util/analyze/)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def default_paths() -> List[str]:
    """The product tree the repo-wide run covers: every .py under the
    ray_tpu package (tests hold intentional-violation fixtures and the
    scripts are covered too — they ride the package)."""
    root = repo_root()
    pkg = os.path.join(root, "ray_tpu")
    out = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "_native")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


def parse_file(path: str, root: Optional[str] = None) -> Optional[ParsedModule]:
    root = root or repo_root()
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError):
        return None
    rel = os.path.relpath(os.path.abspath(path), root)
    return ParsedModule(path, rel.replace(os.sep, "/"), source, tree)


def _select_passes(rules: Optional[Sequence[str]]):
    if not rules:
        return dict(PASSES)
    unknown = [r for r in rules if r not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; have {sorted(PASSES)}")
    return {r: PASSES[r] for r in rules}


def run_modules(modules: Sequence[ParsedModule],
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    selected = _select_passes(rules)
    findings: List[Finding] = []
    for mod in modules:
        for fn in selected.values():
            for f in fn(mod):
                if not mod.ignored(f.rule, f.line):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    return findings


def run_paths(paths: Sequence[str],
              rules: Optional[Sequence[str]] = None,
              root: Optional[str] = None) -> List[Finding]:
    """Run the selected passes over the target files; returns findings
    sorted by location. Unknown rule names raise ValueError (a typo'd
    --rule must not silently pass)."""
    root = root or repo_root()
    modules = [m for m in (parse_file(p, root) for p in paths)
               if m is not None]
    return run_modules(modules, rules)


def baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), BASELINE_FILENAME)


def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    """{finding key: one-line justification}. Missing file = empty."""
    path = path or baseline_path()
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    entries = data.get("entries", data) if isinstance(data, dict) else {}
    return {str(k): str(v) for k, v in entries.items()
            if not str(k).startswith("_")}


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[str, str]):
    """Split findings into (new, allowlisted) and report stale baseline
    keys that matched nothing (the allowlist must only shrink)."""
    new: List[Finding] = []
    allowed: List[Finding] = []
    seen: set = set()
    for f in findings:
        if f.key in baseline:
            allowed.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    stale = sorted(k for k in baseline if k not in seen)
    return new, allowed, stale


def changed_lines(rev: str,
                  root: Optional[str] = None) -> Dict[str, Optional[set]]:
    """{repo-relative path: set of changed/added line numbers} since
    ``rev``, from a cheap ``git diff -U0`` parse (the ``--diff`` mode:
    a PR sees findings on the lines it touched, not the whole repo).
    Brand-new UNTRACKED .py files — which ``git diff`` omits entirely —
    map to ``None``, meaning every line counts as changed (a new module
    is 100%% the PR's lines; silently skipping it would false-pass the
    exact violations the PR introduced)."""
    root = root or repo_root()
    try:
        out = subprocess.run(
            ["git", "diff", "-U0", rev, "--", "*.py"],
            cwd=root, capture_output=True, text=True, timeout=60)
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard",
             "--", "*.py"],
            cwd=root, capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"git diff against {rev!r} failed: {e}")
    if out.returncode != 0:
        raise RuntimeError(
            f"git diff against {rev!r} failed: {out.stderr.strip()}")
    changed: Dict[str, Optional[set]] = {}
    if untracked.returncode == 0:
        for path in untracked.stdout.splitlines():
            if path.strip():
                changed[path.strip()] = None  # all lines are new
    current: Optional[str] = None
    for line in out.stdout.splitlines():
        if line.startswith("+++ "):
            target = line[4:].strip()
            current = None if target == "/dev/null" else \
                target[2:] if target.startswith("b/") else target
        elif line.startswith("@@") and current is not None:
            m = re.search(r"\+(\d+)(?:,(\d+))?", line)
            if not m:
                continue
            start = int(m.group(1))
            count = int(m.group(2)) if m.group(2) is not None else 1
            if count <= 0:
                # Pure-deletion hunk (`+N,0`): no line in the new file
                # was touched — marking N "changed" would pin someone
                # else's finding on a deletion-only PR.
                continue
            changed.setdefault(current, set()).update(
                range(start, start + count))
    return changed


def filter_to_diff(findings: Sequence[Finding],
                   changed: Dict[str, Optional[set]]) -> List[Finding]:
    out = []
    for f in findings:
        lines = changed.get(f.path, ())
        if lines is None or f.line in lines:  # None = whole file is new
            out.append(f)
    return out


def rule_counts(findings: Sequence[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return dict(sorted(counts.items()))


def _cross_ignored(modules: Sequence[ParsedModule], f: Finding) -> bool:
    """Honor line pragmas for cross-module findings too: the module the
    finding anchors to is in view on a full scan by construction."""
    for m in modules:
        if m.relpath == f.path:
            return m.ignored(f.rule, f.line)
    return False


def run(paths: Optional[Sequence[str]] = None,
        rules: Optional[Sequence[str]] = None,
        use_baseline: bool = True,
        baseline_file: Optional[str] = None,
        diff_rev: Optional[str] = None,
        root: Optional[str] = None) -> dict:
    """One-call API (the CLI, perfsuite stage and tier-1 test share it).

    Returns ``{findings, new, allowed, stale_baseline, rule_counts,
    ok}`` where ``ok`` means zero unbaselined findings (stale baseline
    keys are reported but don't fail — a fix must not break the gate)."""
    root = root or repo_root()
    full_scan = not paths
    paths = list(paths) if paths else default_paths()
    modules = [m for m in (parse_file(p, root) for p in paths)
               if m is not None]
    findings = run_modules(modules, rules)
    if full_scan:
        # Cross-module checks: they need the whole tree in view, so
        # they only run on full scans (a path-restricted run would
        # report every site it didn't happen to look at as stale).
        for name, fn in CROSS_PASSES.items():
            if not rules or name in rules:
                findings.extend(f for f in fn(modules)
                                if not _cross_ignored(modules, f))
        findings.sort(key=lambda f: (f.path, f.line, f.rule, f.detail))
    if diff_rev:
        findings = filter_to_diff(findings, changed_lines(diff_rev, root))
    baseline = load_baseline(baseline_file or baseline_path(root)) \
        if use_baseline else {}
    new, allowed, stale = apply_baseline(findings, baseline)
    # Stale-key reporting is only meaningful when the run could have
    # matched the key: a diff- or rule-restricted run hides findings by
    # design, and a path-restricted run never saw other files — advising
    # "remove it" there would delete still-needed justifications.
    if diff_rev or rules:
        stale = []
    elif not full_scan:
        scanned = {m.relpath for m in modules}
        stale = [k for k in stale
                 if ":" in k and k.split(":")[1] in scanned]
    return {
        "findings": findings,
        "new": new,
        "allowed": allowed,
        "stale_baseline": stale,
        "rule_counts": rule_counts(findings),
        "new_rule_counts": rule_counts(new),
        "n_files": len(paths),
        "ok": not new,
    }
