"""Concurrency & contract static analysis (``ray-tpu analyze``).

The framework's worst shipped bugs were never logic errors — they were
concurrency-contract violations found only at runtime: the PR-5
GC-finalizer deadlock (a non-reentrant lock reachable from ``__del__``/
``weakref.finalize`` callbacks wedged the whole local backend), the
lock-order discipline the round-6 head shard split could only *document*
in a comment, and blocking RPC/sqlite work under a shard lock that
serialized the control plane. This package turns those postmortems into
AST-level passes that run in tier-1, the same way ``bench_log --check``
turned evidence hygiene into a gate.

Passes (rule-id prefix):

* ``lock-order`` (LO/GB) — lock acquisition partial order against the
  declared ``LOCK_ORDER`` tuple + discovered nesting; non-reentrant
  same-lock re-entry; ``# guarded-by: <lock>`` declared-intent checks.
* ``blocking`` (BL) — RPC calls, thread joins / future results, event
  waits, sleeps and sqlite commits inside a lock's critical section.
* ``finalizer`` (FS) — code reachable from ``__del__`` / ``weakref
  .finalize`` callbacks must only take RLock-protocol locks and must
  never make RPC calls (the PR-5 deadlock, now a rule).
* ``async-lock`` (AH) — ``await`` / blocking calls while a sync lock is
  held inside ``async def`` (the serve/router path bug class).
* ``contracts`` (CD) — every ``failpoints.hit(site)`` registered in
  ``failpoints.SITES``; every metric family emitted with exactly its
  declared tag keys and declared in the (grafana-feeding) registry;
  two-sided recorders observing locally AND buffering for replay.

Heuristic and precise-by-allowlist rather than sound-and-noisy: the
committed ``ANALYZE_BASELINE.json`` allowlists justified findings so
only *new* violations fail; in-code pragmas
(``# analyze: allow-blocking(<why>)`` on a lock declaration,
``# analyze: ignore[RULE]`` on a finding line) record intent next to
the code they bless.

Entry points: ``ray-tpu analyze [--rule ...] [--baseline] [--json]
[--diff REV]`` and ``python -m ray_tpu.scripts.analyze``; the repo-wide
run is asserted clean by ``tests/test_static_analysis.py``.
"""

from ray_tpu.util.analyze.core import (  # noqa: F401
    Finding,
    PASSES,
    analysis_pass,
    default_paths,
    load_baseline,
    run,
    run_paths,
)

# Importing the pass modules registers them with the PASSES registry.
from ray_tpu.util.analyze import (  # noqa: F401,E402
    blocking,
    contracts,
    finalizers,
    lock_order,
)
