"""Concurrency & contract static analysis (``ray-tpu analyze``).

The framework's worst shipped bugs were never logic errors — they were
concurrency-contract violations found only at runtime: the PR-5
GC-finalizer deadlock (a non-reentrant lock reachable from ``__del__``/
``weakref.finalize`` callbacks wedged the whole local backend), the
lock-order discipline the round-6 head shard split could only *document*
in a comment, and blocking RPC/sqlite work under a shard lock that
serialized the control plane. This package turns those postmortems into
AST-level passes that run in tier-1, the same way ``bench_log --check``
turned evidence hygiene into a gate.

Passes (rule-id prefix):

* ``lock-order`` (LO/GB) — lock acquisition partial order against the
  declared ``LOCK_ORDER`` tuple + discovered nesting; non-reentrant
  same-lock re-entry; ``# guarded-by: <lock>`` declared-intent checks.
* ``blocking`` (BL) — RPC calls, thread joins / future results, event
  waits, sleeps and sqlite commits inside a lock's critical section.
* ``finalizer`` (FS) — code reachable from ``__del__`` / ``weakref
  .finalize`` callbacks must only take RLock-protocol locks and must
  never make RPC calls (the PR-5 deadlock, now a rule).
* ``async-lock`` (AH) — ``await`` / blocking calls while a sync lock is
  held inside ``async def`` (the serve/router path bug class).
* ``contracts`` (CD) — every ``failpoints.hit(site)`` registered in
  ``failpoints.SITES``; every metric family emitted with exactly its
  declared tag keys and declared in the (grafana-feeding) registry;
  two-sided recorders observing locally AND buffering for replay.
* ``retry`` (RT) — retried RPC call sites must target handlers
  declared ``# idempotent`` (which must visibly absorb replays) or
  consult ``maybe_executed``; bounded resubmits must narrow what they
  retry (the PR-13 blind-resubmit / severed-2PC-commit class).
* ``daemon-loop`` (DL) — forever-loops doing RPC/IO must survive
  exceptions, and every survival handler must count into
  ``ray_tpu_loop_restarts_total{loop}`` (a crash-restart cycle must
  be visible on the scrape).
* ``timeout-order`` (TO) — ``# timeout-budget: outlasts <ref>``
  relations checked against config defaults: an inner RPC timeout can
  never undercut the outer budget it serves (the PR-14
  task-unblocked-kills-healthy-task shape).
* ``jax-hotpath`` (JX) — unmarked-static jit scalars, host syncs and
  sleepless poll spins in ``# jax-hot-path`` regions, fp32 upcasts in
  ``# decode-path`` (activation-dtype) regions — the per-request
  recompile / GIL-starvation throughput class PR 13's compile
  counters guard at runtime.
* ``lifecycle`` (LC) — per-entity gauge families must appear in a
  retraction sweep; ship-buffer drains must requeue on upload
  failure; ``# slot-guard`` declared acquire/release pairs must keep
  their failure-edge release.
* ``timing`` (TH) — step-timing honesty: a ``# step-timed`` region's
  timer reads must bracket a real host sync (``block_until_ready`` /
  ``.item()`` / ``np.asarray`` / ``float()`` of a device scalar) — an
  unsynced wall around async dispatch times the launch, not the
  device, and the MFU/anatomy plane built on it would be fiction; a
  marked region that times nothing is a stale annotation.
* ``trace-propagation`` (TP) — manual flight-recorder spans
  (``tracing.start_span``) must be closable: never-finished local
  spans, finishes that aren't exception-safe (no ``finally`` and no
  except/normal pair), and created-and-discarded span handles — the
  leak shapes that stall trace assembly's quiet window.

Heuristic and precise-by-allowlist rather than sound-and-noisy: the
committed ``ANALYZE_BASELINE.json`` allowlists justified findings so
only *new* violations fail; in-code pragmas
(``# analyze: allow-blocking(<why>)`` on a lock declaration,
``# analyze: ignore[RULE]`` on a finding line) record intent next to
the code they bless.

Entry points: ``ray-tpu analyze [--rule ...] [--baseline] [--json]
[--diff REV]`` and ``python -m ray_tpu.scripts.analyze``; the repo-wide
run is asserted clean by ``tests/test_static_analysis.py``.
"""

from ray_tpu.util.analyze.core import (  # noqa: F401
    Finding,
    PASSES,
    analysis_pass,
    default_paths,
    load_baseline,
    run,
    run_paths,
)

# Importing the pass modules registers them with the PASSES registry.
from ray_tpu.util.analyze import (  # noqa: F401,E402
    blocking,
    contracts,
    daemon_loops,
    finalizers,
    jax_hotpath,
    lifecycle,
    lock_order,
    retry,
    timeouts,
    timing,
    trace_propagation,
)
