"""Shared guarded-by / lock-alias resolver for the concurrency passes.

Heuristic by design (and precise-by-allowlist): it tracks the idioms
this codebase actually uses —

* ``self._lock = threading.Lock() / RLock() / _ShardLock(...)`` lock
  attributes (reentrancy from the factory name);
* ``self._cv = threading.Condition(self._lock)`` aliases: acquiring the
  condition acquires the underlying lock, and ``cv.wait()`` *releases*
  it (so a wait under its own lock is not blocking-under-lock);
* module-level locks (``_buf_lock = threading.Lock()``);
* ``with self._lock:`` critical sections, nested and multi-item;
* helper calls one level deep: ``self._helper()`` under a lock imports
  the helper's own acquisitions and blocking calls to the call site;
* ``# guarded-by: <lock>`` annotations on attribute declarations
  (declared intent for pass 1) and ``# analyze: allow-blocking`` on a
  lock declaration (this lock's entire job is serializing the blocking
  I/O under it — e.g. a dedicated sqlite connection mutex).

Anything it cannot resolve it stays silent about: an unrecognized
context manager is not a lock, an unrecognized receiver is not a
thread, and manual ``lock.acquire()``/``release()`` pairing is out of
scope (this codebase's critical sections are ``with`` blocks). False
negatives are acceptable; false positives go to the baseline with a
justification.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

# Factory callables recognized as lock constructors: name -> reentrant.
LOCK_FACTORIES = {
    "Lock": False,
    "RLock": True,
    "_ShardLock": True,  # head.py: RLock-protocol instrumented shard
    "ShardLock": True,
}

# Methods that mutate a container in place (guarded-by writes).
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popitem", "popleft", "clear", "update",
    "setdefault", "move_to_end", "rotate",
})

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][\w.]*)")
_ALLOW_BLOCKING_RE = re.compile(r"#\s*analyze:\s*allow-blocking")


def callee_name(call: ast.Call) -> str:
    """Trailing name of the called expression ('' when dynamic)."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def receiver_of(call: ast.Call) -> Optional[ast.expr]:
    if isinstance(call.func, ast.Attribute):
        return call.func.value
    return None


def _self_attr(expr: ast.expr) -> Optional[str]:
    """'X' for a `self.X` expression, else None."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


class LockInfo:
    __slots__ = ("name", "reentrant", "allow_blocking", "line", "owner")

    def __init__(self, name: str, reentrant: Optional[bool], line: int,
                 owner: str, allow_blocking: bool = False):
        self.name = name
        self.reentrant = reentrant  # None = unknown protocol
        self.allow_blocking = allow_blocking
        self.line = line
        self.owner = owner  # "Class" or "" for module scope

    @property
    def qualname(self) -> str:
        return f"{self.owner}.{self.name}" if self.owner else self.name


class ClassModel:
    """Lock/alias/annotation facts for one class."""

    def __init__(self, node: ast.ClassDef, lines: List[str]):
        self.node = node
        self.name = node.name
        self.locks: Dict[str, LockInfo] = {}
        self.conds: Dict[str, Optional[str]] = {}  # cv attr -> lock attr
        self.events: set = set()  # threading.Event attrs
        self.threads: set = set()  # threading.Thread attrs
        self.guarded_by: Dict[str, str] = {}  # data attr -> lock name
        self.methods: Dict[str, ast.FunctionDef] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        for meth in self.methods.values():
            for stmt in ast.walk(meth):
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    self._scan_assign(stmt, lines)

    def _scan_assign(self, stmt, lines: List[str]) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None:
                continue
            text = lines[stmt.lineno - 1] if stmt.lineno <= len(lines) \
                else ""
            m = _GUARDED_BY_RE.search(text)
            if m:
                self.guarded_by.setdefault(attr, m.group(1))
            if not isinstance(value, ast.Call):
                continue
            name = callee_name(value)
            if name in LOCK_FACTORIES:
                self.locks[attr] = LockInfo(
                    attr, LOCK_FACTORIES[name], stmt.lineno, self.name,
                    allow_blocking=bool(_ALLOW_BLOCKING_RE.search(text)))
            elif name == "Condition":
                arg = value.args[0] if value.args else None
                under = _self_attr(arg) if arg is not None else None
                if arg is None:
                    # Condition() owns a fresh RLock: model the cv as a
                    # reentrant lock in its own right.
                    self.locks[attr] = LockInfo(
                        attr, True, stmt.lineno, self.name,
                        allow_blocking=bool(
                            _ALLOW_BLOCKING_RE.search(text)))
                    self.conds[attr] = attr
                elif under is not None:
                    self.conds[attr] = under
            elif name == "Event":
                self.events.add(attr)
            elif name == "Thread":
                self.threads.add(attr)


class ModuleModel:
    """Per-module lock facts: module-scope locks, classes, LOCK_ORDER.
    Also caches the function walk and per-class method summaries so the
    passes share one resolver pass per file."""

    def __init__(self, tree: ast.Module, lines: List[str]):
        self.tree = tree
        self.lines = lines
        self.module_locks: Dict[str, LockInfo] = {}
        self.module_events: set = set()
        self.classes: Dict[str, ClassModel] = {}
        self.lock_order: Tuple[str, ...] = ()
        self._functions = None
        self._summaries: Dict[int, Dict[str, "MethodSummary"]] = {}
        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.classes[stmt.name] = ClassModel(stmt, lines)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                self._scan_module_assign(stmt)

    def _scan_module_assign(self, stmt) -> None:
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        value = stmt.value
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue
            if tgt.id == "LOCK_ORDER" and isinstance(
                    value, (ast.Tuple, ast.List)):
                order = []
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(
                            elt.value, str):
                        order.append(elt.value)
                self.lock_order = tuple(order)
                continue
            if not isinstance(value, ast.Call):
                continue
            name = callee_name(value)
            text = self.lines[stmt.lineno - 1] \
                if stmt.lineno <= len(self.lines) else ""
            if name in LOCK_FACTORIES:
                self.module_locks[tgt.id] = LockInfo(
                    tgt.id, LOCK_FACTORIES[name], stmt.lineno, "",
                    allow_blocking=bool(_ALLOW_BLOCKING_RE.search(text)))
            elif name == "Condition" and not value.args:
                self.module_locks[tgt.id] = LockInfo(
                    tgt.id, True, stmt.lineno, "")
            elif name == "Event":
                self.module_events.add(tgt.id)

    def functions(self):
        """Cached :func:`all_functions` over this module."""
        if self._functions is None:
            self._functions = all_functions(self.tree, self, self.lines)
        return self._functions

    def summaries_for(self, cls: "ClassModel"):
        """Cached :func:`summarize_methods` for one of this module's
        classes (keyed by the class NODE: an ad-hoc nested class must
        not collide with a top-level class of the same name)."""
        key = id(cls.node)
        if key not in self._summaries:
            self._summaries[key] = summarize_methods(cls, self)
        return self._summaries[key]


class LockRef:
    """One resolved lock acquisition target."""

    __slots__ = ("info", "via")

    def __init__(self, info: LockInfo, via: str = ""):
        self.info = info
        self.via = via  # the condition attr it was reached through

    @property
    def name(self) -> str:
        return self.info.name

    @property
    def qualname(self) -> str:
        return self.info.qualname


class FunctionContext:
    """Resolution scope for one function body."""

    def __init__(self, module: ModuleModel, cls: Optional[ClassModel]):
        self.module = module
        self.cls = cls

    def resolve_lock(self, expr: ast.expr) -> Optional[LockRef]:
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            if attr in self.cls.conds:
                under = self.cls.conds[attr]
                info = self.cls.locks.get(under)
                if info is not None:
                    return LockRef(info, via=attr)
                return None
            info = self.cls.locks.get(attr)
            if info is not None:
                return LockRef(info)
            return None
        if isinstance(expr, ast.Name):
            info = self.module.module_locks.get(expr.id)
            if info is not None:
                return LockRef(info)
        return None

    def is_event(self, expr: ast.expr) -> bool:
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            return attr in self.cls.events
        if isinstance(expr, ast.Name):
            return expr.id in self.module.module_events
        return False

    def is_thread(self, expr: ast.expr, local_threads: set) -> bool:
        attr = _self_attr(expr)
        if attr is not None and self.cls is not None:
            if attr in self.cls.threads:
                return True
            return "thread" in attr.lower() or "flusher" in attr.lower()
        if isinstance(expr, ast.Name):
            if expr.id in local_threads:
                return True
            return "thread" in expr.id.lower()
        return False


class Event:
    """One fact the walker surfaced inside a function body."""

    __slots__ = ("kind", "node", "held", "data")

    def __init__(self, kind: str, node: ast.AST,
                 held: Tuple[LockRef, ...], data):
        self.kind = kind  # acquire|blocking|await|self_call|mutate
        self.node = node
        self.held = held
        self.data = data


def classify_blocking(call: ast.Call, ctx: FunctionContext,
                      local_threads: set,
                      held: Tuple[LockRef, ...]) -> Optional[Tuple[str, str]]:
    """(kind, detail) when the call is a known blocking primitive.

    ``wait`` on the condition of a lock currently held through that
    condition's OWN lock is exempt for that lock (Condition.wait
    releases it) — the caller still gets a finding for any *other*
    lock held across the wait, which is exactly the two-lock hazard.
    """
    name = callee_name(call)
    recv = receiver_of(call)
    if name in ("call", "call_stream"):
        return ("rpc", name)
    if name == "sleep":
        if recv is None or (isinstance(recv, ast.Name)
                            and recv.id == "time"):
            return ("sleep", "time.sleep")
        return None
    if name == "result":
        return ("future", "result")
    if name == "commit" and recv is not None:
        return ("sqlite", "commit")
    if name == "join" and recv is not None:
        if ctx.is_thread(recv, local_threads):
            return ("join", "thread.join")
        return None
    if name == "wait" and recv is not None:
        if ctx.is_event(recv):
            return ("wait", "event.wait")
        lr = ctx.resolve_lock(recv)
        if lr is not None and lr.via:
            # Condition.wait: releases its own lock; blocking only for
            # the OTHER locks held across it.
            others = [h for h in held if h.qualname != lr.qualname]
            if others:
                return ("wait", f"cond.wait holding {others[0].qualname}")
            return None
        return None
    return None


def _local_threads(fn: ast.AST) -> set:
    out = set()
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign) and isinstance(
                stmt.value, ast.Call):
            if callee_name(stmt.value) == "Thread":
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _expr_calls(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Call/Await nodes in a statement's expressions, NOT descending
    into nested function/class definitions or nested statements (the
    statement walker handles those with their own held context)."""

    class V(ast.NodeVisitor):
        def __init__(self):
            self.found: List[ast.AST] = []

        def visit_FunctionDef(self, node):
            pass

        def visit_AsyncFunctionDef(self, node):
            pass

        def visit_Lambda(self, node):
            pass

        def visit_ClassDef(self, node):
            pass

        def visit_Call(self, node):
            self.found.append(node)
            self.generic_visit(node)

        def visit_Await(self, node):
            self.found.append(node)
            self.generic_visit(node)

    v = V()
    # Visit only the statement's direct expression fields; child
    # statements are walked by iter_events with their own held state.
    for field, value in ast.iter_fields(stmt):
        if field in ("body", "orelse", "finalbody", "handlers", "items"):
            continue
        if isinstance(value, ast.AST):
            v.visit(value)
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.AST) and isinstance(
                        item, ast.expr):
                    v.visit(item)
    return iter(v.found)


def _mutation_target(stmt: ast.stmt) -> Iterable[Tuple[str, ast.AST]]:
    """Attr names of `self.X` containers this statement mutates."""
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for tgt in targets:
            # self.X[k] = v / self.X[k] += v
            if isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
                if attr is not None:
                    yield (attr, stmt)
            else:
                attr = _self_attr(tgt)
                if attr is not None:
                    yield (attr, stmt)
    elif isinstance(stmt, ast.Delete):
        for tgt in stmt.targets:
            if isinstance(tgt, ast.Subscript):
                attr = _self_attr(tgt.value)
                if attr is not None:
                    yield (attr, stmt)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        call = stmt.value
        if callee_name(call) in MUTATOR_METHODS:
            recv = receiver_of(call)
            if recv is not None:
                attr = _self_attr(recv)
                if attr is not None:
                    yield (attr, stmt)


def iter_events(fn: ast.AST, ctx: FunctionContext,
                held0: Tuple[LockRef, ...] = ()) -> Iterator[Event]:
    """Walk one function body yielding acquisition / blocking / await /
    self-call / mutation events with the set of locks held at each."""
    local_threads = _local_threads(fn)

    def scan_exprs(stmt: ast.stmt, held) -> Iterator[Event]:
        for node in _expr_calls(stmt):
            if isinstance(node, ast.Await):
                yield Event("await", node, held, None)
                continue
            call = node
            blocked = classify_blocking(call, ctx, local_threads, held)
            if blocked is not None:
                yield Event("blocking", call, held, blocked)
            recv = receiver_of(call)
            if recv is not None and isinstance(recv, ast.Name) \
                    and recv.id == "self":
                yield Event("self_call", call, held,
                            callee_name(call))
            elif isinstance(call.func, ast.Name):
                # Bare-name call: a closure invoked in place (builtins
                # land here too — consumers look names up against known
                # functions, so the noise is inert).
                yield Event("local_call", call, held, call.func.id)

    def walk(stmts, held) -> Iterator[Event]:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef, ast.Lambda)):
                continue  # deferred execution: own context
            for attr, node in _mutation_target(stmt):
                yield Event("mutate", node, held, attr)
            yield from scan_exprs(stmt, held)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = list(held)
                for item in stmt.items:
                    lr = ctx.resolve_lock(item.context_expr)
                    # `async with` managers are asyncio primitives, not
                    # threading locks — only sync `with` acquires here.
                    if lr is not None and isinstance(stmt, ast.With):
                        yield Event("acquire", item.context_expr,
                                    tuple(acquired), lr)
                        acquired.append(lr)
                yield from walk(stmt.body, tuple(acquired))
            elif isinstance(stmt, ast.Try):
                yield from walk(stmt.body, held)
                for handler in stmt.handlers:
                    yield from walk(handler.body, held)
                yield from walk(stmt.orelse, held)
                yield from walk(stmt.finalbody, held)
            elif isinstance(stmt, (ast.If,)):
                yield from walk(stmt.body, held)
                yield from walk(stmt.orelse, held)
            elif isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
                yield from walk(stmt.body, held)
                yield from walk(stmt.orelse, held)
            elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
                for case in stmt.cases:
                    yield from walk(case.body, held)

    yield from walk(getattr(fn, "body", []), tuple(held0))


class MethodSummary:
    """What one method does, for one-level helper expansion."""

    __slots__ = ("acquires", "blocking", "awaits")

    def __init__(self):
        self.acquires: List[Tuple[LockRef, int]] = []
        self.blocking: List[Tuple[str, str, int]] = []
        self.awaits: List[int] = []


def summarize_methods(cls: ClassModel,
                      module: ModuleModel) -> Dict[str, MethodSummary]:
    out: Dict[str, MethodSummary] = {}
    for name, fn in cls.methods.items():
        ctx = FunctionContext(module, cls)
        s = MethodSummary()
        for ev in iter_events(fn, ctx):
            if ev.kind == "acquire":
                s.acquires.append((ev.data, ev.node.lineno))
            elif ev.kind == "blocking":
                kind, detail = ev.data
                # Export only blocking calls the helper makes while
                # holding NO lock of its own: a call under the helper's
                # allow-blocking lock is that lock's job, and a call
                # under any other helper-held lock already gets its own
                # direct finding in the helper's scope.
                if ev.held:
                    continue
                s.blocking.append((kind, detail, ev.node.lineno))
            elif ev.kind == "await":
                s.awaits.append(ev.node.lineno)
        out[name] = s
    return out


def all_functions(mod_tree: ast.Module, model: ModuleModel,
                  lines: List[str]):
    """Every function/coroutine in the module — top-level, methods AND
    nested closures (drain-coordinator threads, serve's nested ``async
    def app`` live inside methods) — each paired with its dotted scope
    path and the ClassModel of its nearest enclosing class (``self`` in
    a closure still binds the method's instance)."""
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(mod_tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    adhoc: Dict[int, ClassModel] = {}
    out = []
    for fn in ast.walk(mod_tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        path = [fn.name]
        cls_node = None
        cur = parents.get(fn)
        while cur is not None and not isinstance(cur, ast.Module):
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                path.append(cur.name)
                if cls_node is None and isinstance(cur, ast.ClassDef):
                    cls_node = cur
            cur = parents.get(cur)
        scope = ".".join(reversed(path))
        cm = None
        if cls_node is not None:
            cm = model.classes.get(cls_node.name)
            if cm is None or cm.node is not cls_node:
                key = id(cls_node)
                if key not in adhoc:
                    adhoc[key] = ClassModel(cls_node, lines)
                cm = adhoc[key]
        out.append((cm, fn, scope))
    out.sort(key=lambda t: t[1].lineno)
    return out


def iter_functions(tree: ast.Module) -> Iterator[Tuple[
        Optional[ast.ClassDef], ast.AST, str]]:
    """(enclosing class | None, function node, scope string) for every
    top-level and class-level function (nested defs ride their parent's
    walk)."""
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield None, stmt, stmt.name
        elif isinstance(stmt, ast.ClassDef):
            for item in stmt.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    yield stmt, item, f"{stmt.name}.{item.name}"
