"""Pass 9 — JAX hot-path lint (JX): the recompile/host-sync/GIL class.

PR 13's engine carries trace-time compile counters precisely because
the per-request-recompile bug is trivially easy to reintroduce and
invisible until the bench runs: one stray Python-scalar jit argument,
one host sync inside the step loop, one sleepless poll spin — each a
throughput bug per the TPU-concurrency-limits framing (a GIL-starved
engine loop measured 3x tokens/s). The declared regions make the
discipline static:

* ``# jax-hot-path`` on (or directly above) a ``def`` marks an
  engine/decode-step region: code executed once per decode iteration
  or traced into the jitted step.
* ``# decode-path`` marks a function declared to stay in the model's
  activation dtype (the KV-cache contract: bf16, no fp32 copy ever
  materializes).

Rules:

* **JX001** — a callable jitted WITHOUT ``static_argnums``/
  ``static_argnames`` is invoked with a Python int/bool literal
  argument: every distinct value shape-specializes or retraces (and a
  value meant to select branches/shapes silently recompiles per
  request — the compile-counter claim breaks).
* **JX002** — a host sync inside a ``# jax-hot-path`` region:
  ``np.asarray`` / ``np.array`` / ``jax.device_get`` /
  ``.block_until_ready()`` / ``.item()``. Each one stalls the Python
  thread on the device stream mid-iteration; syncs belong at the
  step boundary, once (mark the single intentional one with
  ``# analyze: ignore[JX002]``).
* **JX003** — a sleepless poll spin: a ``while`` loop that calls a
  ``*poll*`` API with no ``time.sleep`` / ``.wait(...)`` / blocking
  long-poll (timeout kwarg) anywhere in its body. A tight poll loop
  on the GIL starves the engine thread (the measured 3x tokens/s
  collector bug).
* **JX004** — an fp32 upcast inside a ``# decode-path`` region:
  ``float32`` mentioned in a region declared activation-dtype means a
  2x HBM copy of cache-sized state (deliberate fp32 reductions live
  OUTSIDE the declared region, or carry an ignore pragma with the
  reason).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from ray_tpu.util.analyze.core import (
    FindingSink,
    ParsedModule,
    analysis_pass,
)
from ray_tpu.util.analyze.resolver import callee_name, receiver_of

_HOT_MARK = "# jax-hot-path"
_DECODE_MARK = "# decode-path"

_HOST_SYNC_ATTRS = frozenset({"block_until_ready", "item"})
_NP_ALIASES = frozenset({"np", "numpy", "onp"})


def _marked(mod: ParsedModule, fn: ast.AST, mark: str) -> bool:
    for ln in (fn.lineno, fn.lineno - 1):
        if mark in mod.line_text(ln):
            return True
    # Decorated defs: the marker may sit above the decorator stack.
    deco = getattr(fn, "decorator_list", None)
    if deco:
        top = min(d.lineno for d in deco)
        if mark in mod.line_text(top - 1):
            return True
    return False


def _jit_call(value: ast.expr) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` / ``jit(...)`` call in an assignment value
    (None when it isn't one)."""
    if not isinstance(value, ast.Call):
        return None
    fn = value.func
    if isinstance(fn, ast.Attribute) and fn.attr == "jit":
        return value
    if isinstance(fn, ast.Name) and fn.id == "jit":
        return value
    return None


def _jit_has_static(call: ast.Call) -> bool:
    return any(kw.arg in ("static_argnums", "static_argnames")
               for kw in call.keywords)


def _scalar_literal_args(call: ast.Call) -> List[int]:
    """Line numbers of Python int/bool literal args (positional or
    keyword) — the unmarked-static recompile shape."""
    out = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Constant) and isinstance(
                a.value, (int, bool)) and not isinstance(a.value, float):
            out.append(call.lineno)
            break
    return out


def _nonzero_timeout_kwarg(call: ast.Call) -> bool:
    """A timeout-ish kwarg that isn't literally zero (``wait(timeout=0)``
    is exactly the non-blocking poll the spin rule exists to catch)."""
    for kw in call.keywords:
        if not (kw.arg and "timeout" in kw.arg):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and v.value in (0, 0.0):
            continue
        return True
    return False


def _blocking_poll_exempt(call: ast.Call) -> bool:
    """A poll call that itself blocks (carries a non-zero timeout-ish
    kwarg) is a long-poll, not a spin."""
    return _nonzero_timeout_kwarg(call)


def _fn_has_block(fn: ast.AST) -> bool:
    """The function's own body blocks somewhere: a sleep, a wait, or
    any call with a timeout-ish kwarg (a queue.get(timeout=...) drain,
    an owner long-poll). One level of this keeps the spin rule honest
    about loops whose blocking lives in a helper."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = callee_name(node)
        if name in ("sleep", "wait"):
            return True
        if _nonzero_timeout_kwarg(node):
            return True
    return False


@analysis_pass("jax-hotpath")
def jax_hotpath_pass(mod: ParsedModule) -> List:
    sink = FindingSink(mod.relpath)
    model = mod.model()

    # -- JX001: jit-without-static + scalar-literal invocation ----------
    # Collect jitted names (module/class/local assignments alike; keyed
    # by leaf attr or bare name) that lack static arg declarations.
    unstatic: Set[str] = set()
    statics: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        jc = _jit_call(node.value)
        if jc is None:
            continue
        for tgt in node.targets:
            leaf = None
            if isinstance(tgt, ast.Name):
                leaf = tgt.id
            elif isinstance(tgt, ast.Attribute):
                leaf = tgt.attr
            if leaf is None:
                continue
            (statics if _jit_has_static(jc) else unstatic).add(leaf)
    unstatic -= statics  # a rebound name with statics gets the benefit
    if unstatic:
        parents: dict = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = None
            if isinstance(node.func, ast.Name):
                leaf = node.func.id
            elif isinstance(node.func, ast.Attribute):
                leaf = node.func.attr
            if leaf not in unstatic:
                continue
            if _scalar_literal_args(node):
                scope_node = node
                path: List[str] = []
                cur = parents.get(scope_node)
                while cur is not None and not isinstance(
                        cur, ast.Module):
                    if isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef)):
                        path.append(cur.name)
                    cur = parents.get(cur)
                scope = ".".join(reversed(path)) or "<module>"
                sink.emit(
                    "JX001", node.lineno, scope, leaf,
                    f"jitted callable {leaf} (no static_argnums/"
                    f"static_argnames on its jax.jit) is invoked with "
                    f"a Python scalar literal: every distinct value "
                    f"retraces/specializes — per-request recompile "
                    f"risk (the compile-counter claim breaks)",
                    "declare the scalar static in the jit (or pass a "
                    "jnp array if it's genuinely data)")

    # -- JX002/JX003/JX004: declared-region rules -----------------------
    for cm, fn, scope in model.functions():
        hot = _marked(mod, fn, _HOT_MARK)
        decode = _marked(mod, fn, _DECODE_MARK)
        if hot:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = callee_name(node)
                recv = receiver_of(node)
                is_sync = False
                what = name
                if name in _HOST_SYNC_ATTRS and recv is not None:
                    is_sync = True
                    what = f".{name}()"
                elif name in ("asarray", "array") and isinstance(
                        recv, ast.Name) and recv.id in _NP_ALIASES:
                    is_sync = True
                    what = f"{recv.id}.{name}"
                elif name == "device_get":
                    is_sync = True
                    what = "jax.device_get"
                if is_sync:
                    sink.emit(
                        "JX002", node.lineno, scope,
                        f"{what}:{node.lineno}",
                        f"host sync ({what}) inside the `# jax-hot-"
                        f"path` region {scope}: the Python thread "
                        f"stalls on the device stream mid-iteration — "
                        f"a throughput bug before a correctness one",
                        "hoist the sync to the step boundary (one sync "
                        "per iteration, marked `# analyze: "
                        "ignore[JX002]` with the reason)")
        if decode:
            for i in range(fn.lineno,
                           getattr(fn, "end_lineno", fn.lineno) + 1):
                text = mod.line_text(i)
                if "float32" in text and _DECODE_MARK not in text:
                    sink.emit(
                        "JX004", i, scope, f"float32:{i}",
                        f"fp32 upcast inside `# decode-path` region "
                        f"{scope}: the region is declared to stay in "
                        f"the activation dtype (the KV-cache contract "
                        f"— no fp32 copy of cache-sized state)",
                        "keep decode state in cfg.dtype; a deliberate "
                        "fp32 reduction belongs outside the declared "
                        "region or carries `# analyze: ignore[JX004]` "
                        "with the reason")

    # -- JX003: sleepless poll spins (any function) ---------------------
    for cm, fn, scope in model.functions():
        for loop in ast.walk(fn):
            if not isinstance(loop, ast.While):
                continue
            poll_call = None
            has_block = False
            for node in ast.walk(loop):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if not isinstance(node, ast.Call):
                    continue
                name = callee_name(node)
                recv = receiver_of(node)
                callee_fn = None
                if cm is not None and isinstance(recv, ast.Name) \
                        and recv.id == "self":
                    callee_fn = cm.methods.get(name)
                if name == "sleep" or name == "wait":
                    has_block = True
                elif callee_fn is not None and _fn_has_block(callee_fn):
                    has_block = True  # helper blocks one level down
                elif "poll" in name.lower():
                    if _blocking_poll_exempt(node):
                        has_block = True
                    elif poll_call is None:
                        poll_call = node
                elif _nonzero_timeout_kwarg(node):
                    has_block = True  # a long-poll bounds the spin
            if poll_call is not None and not has_block:
                sink.emit(
                    "JX003", poll_call.lineno, scope,
                    f"poll:{poll_call.lineno}",
                    f"sleepless poll spin in {scope}: the loop polls "
                    f"({callee_name(poll_call)}) with no sleep/wait/"
                    f"long-poll anywhere in its body — on the GIL this "
                    f"starves the engine thread (the measured 3x "
                    f"tokens/s collector bug)",
                    "add an inter-round time.sleep (50ms drains 10k "
                    "streams fine) or use the blocking long-poll form")
    return sink.findings
