"""Pass 5 — contract drift: failpoints, metric families, recorders.

Three contracts that previously lived only in convention:

* **CD001** — every ``failpoints.hit("<site>")`` literal must be
  registered in ``ray_tpu.util.failpoints.SITES``. A site that isn't
  in the table is invisible to ``ray-tpu chaos list``, to the soak
  schedule, and to anyone deciding what chaos coverage exists.
* **CD003** — every metric emission with a literal ``tags={...}`` must
  carry *exactly* the family's declared tag keys. A missing key raises
  ``ValueError`` at runtime; an extra key is silently dropped by
  ``Metric._key`` — a typo'd tag name loses the dimension with no
  error anywhere (the federation-breaking drift class).
* **CD004** — an UPPERCASE attribute read off the metrics module that
  names no registered family: AttributeError at runtime, and the
  registry-driven grafana dashboard can never have a panel for it.
* **CD005/CD006** — two-sided recorder discipline (the serve/train/
  goodput planes): a module that ships observations over the
  worker-events plane (defines ``drain_events`` + ``apply_events``)
  must do ALL local recording through its ``_emit`` (observe locally
  AND buffer for replay); a function that calls a family directly
  records one-sided — the cluster backend's federated scrape silently
  loses those observations (CD005). ``_emit`` itself must do both
  sides (CD006).

The family/site tables come from the live registry (``ray_tpu.util
.metrics`` / ``ray_tpu.util.failpoints``) — the same source the
grafana generator and the chaos CLI read, so the checked contract and
the served contract cannot diverge.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ray_tpu.util.analyze.core import (
    Finding,
    ParsedModule,
    analysis_pass,
    cross_pass,
)

_EMIT_METHODS = frozenset({"inc", "dec", "set", "observe", "remove"})
_METRIC_ALIASES = frozenset({"metrics", "_metrics"})

_tables_cache: Optional[tuple] = None


def _tables() -> Tuple[Dict[str, tuple], frozenset]:
    """({family attr: declared tag keys}, registered failpoint sites)
    from the live modules — loaded once."""
    global _tables_cache
    if _tables_cache is None:
        from ray_tpu.util import failpoints
        from ray_tpu.util import metrics as m

        families = {
            name: tuple(inst.tag_keys)
            for name, inst in vars(m).items()
            if isinstance(inst, m.Metric)
        }
        sites = frozenset(getattr(failpoints, "SITES", frozenset()))
        _tables_cache = (families, sites)
    return _tables_cache


def _family_ref(expr: ast.expr,
                imported: Dict[str, str]) -> Optional[Tuple[str, bool]]:
    """(family attr name, via-module-alias) when the expression reads a
    metric family: ``_metrics.FAMILY`` / ``metrics.FAMILY`` or a bare
    name imported from the metrics module."""
    if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name):
        if expr.value.id in _METRIC_ALIASES and expr.attr.isupper():
            return (expr.attr, True)
        return None
    if isinstance(expr, ast.Name) and expr.id in imported:
        return (expr.id, False)
    return None


def _metric_imports(tree: ast.Module) -> Dict[str, str]:
    """Names from-imported out of ray_tpu.util.metrics (pubsub.py
    idiom) mapped to the original attr name."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("util.metrics"):
            for alias in node.names:
                if alias.name.isupper():
                    out[alias.asname or alias.name] = alias.name
    return out


def _literal_tag_keys(call: ast.Call,
                      method: str) -> Optional[Tuple[str, ...]]:
    """The literal tag keys this emission passes, () for an explicit
    no-tags call, or None when the tags are dynamic (unknowable)."""
    tags_expr = None
    for kw in call.keywords:
        if kw.arg == "tags":
            tags_expr = kw.value
            break
    if tags_expr is None:
        idx = 0 if method == "remove" else 1
        if len(call.args) > idx:
            tags_expr = call.args[idx]
    if tags_expr is None:
        return ()
    if isinstance(tags_expr, ast.Constant) and tags_expr.value is None:
        return ()
    if not isinstance(tags_expr, ast.Dict):
        return None
    keys: List[str] = []
    for k in tags_expr.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            keys.append(k.value)
        else:
            return None  # dynamic key: unknowable
    return tuple(keys)


def _scope_of(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    path: List[str] = []
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.Module):
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            path.append(cur.name)
        cur = parents.get(cur)
    return ".".join(reversed(path)) or "<module>"


def _hit_site_literals(tree: ast.Module) -> List[str]:
    out = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "hit"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "failpoints"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out.append(node.args[0].value)
    return out


@cross_pass("contracts")
def stale_site_findings(modules) -> List[Finding]:
    """**CD002** — the reverse of CD001, checkable only with the whole
    tree in view (so it runs from ``analyze.run()`` on full scans, not
    per-module): a site registered in ``failpoints.SITES`` that no
    scanned file hits advertises chaos coverage that no longer exists
    — the same one-direction drift the stale-baseline report closes
    for the allowlist."""
    _, sites = _tables()
    hits: set = set()
    fp_mod = None
    for mod in modules:
        if mod.relpath.endswith("util/failpoints.py"):
            fp_mod = mod
            continue  # the docstring example is not a real site
        hits.update(_hit_site_literals(mod.tree))
    findings: List[Finding] = []
    for site in sorted(sites - hits):
        line = 1
        if fp_mod is not None:
            for i, text in enumerate(fp_mod.lines, 1):
                if f'"{site}"' in text:
                    line = i
                    break
        findings.append(Finding(
            "CD002", "ray_tpu/util/failpoints.py", line, "<module>",
            site,
            f"failpoints.SITES registers {site!r} but no scanned file "
            f"hits it — the table advertises chaos coverage that no "
            f"longer exists",
            "remove the stale SITES entry (or restore the hit() site)"))
    return findings


@analysis_pass("contracts")
def contracts_pass(mod: ParsedModule) -> List[Finding]:
    families, sites = _tables()
    findings: List[Finding] = []
    imported = _metric_imports(mod.tree)
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    is_failpoints_module = mod.relpath.endswith("util/failpoints.py")
    is_metrics_module = mod.relpath.endswith("util/metrics.py")

    # Two-sided recorder discovery: ships (drain_events) and replays
    # (apply_events) — then every local observation must ride _emit.
    top_funcs = {s.name: s for s in mod.tree.body
                 if isinstance(s, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))}
    is_recorder = ("drain_events" in top_funcs
                   and "apply_events" in top_funcs
                   and not is_metrics_module)
    recorder_allowed = {"apply_events", "retract_gauges"}

    if is_recorder:
        emit_fn = top_funcs.get("_emit")
        if emit_fn is None:
            findings.append(Finding(
                "CD006", mod.relpath, 1, "<module>", "_emit",
                "two-sided recorder module (defines drain_events + "
                "apply_events) has no _emit: nothing enforces that "
                "observations land locally AND in the ship buffer",
                "add _emit(ev) that calls apply_events([ev], ...) and "
                "appends to the ship buffer"))
        else:
            names = {n.id for n in ast.walk(emit_fn)
                     if isinstance(n, ast.Name)}
            attrs = {n.attr for n in ast.walk(emit_fn)
                     if isinstance(n, ast.Attribute)}
            observes = "apply_events" in names
            buffers = any(x.startswith("_buf") for x in names | attrs)
            if not (observes and buffers):
                missing = ("local observe (apply_events call)"
                           if not observes else
                           "ship-buffer append (_buf)")
                findings.append(Finding(
                    "CD006", mod.relpath, emit_fn.lineno, "_emit",
                    "two-sided",
                    f"recorder _emit is one-sided: missing the "
                    f"{missing} half — observations will exist on one "
                    f"backend and silently not the other",
                    "observe into the local registry AND buffer for "
                    "the worker-events replay in the same _emit"))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        # -- failpoint sites ------------------------------------------
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr == "hit"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "failpoints"
                and not is_failpoints_module):
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
                if site not in sites:
                    findings.append(Finding(
                        "CD001", mod.relpath, node.lineno,
                        _scope_of(node, parents), site,
                        f"failpoint site {site!r} is not registered in "
                        f"failpoints.SITES — invisible to `ray-tpu "
                        f"chaos list`, the soak schedule and chaos "
                        f"coverage review",
                        "add the site to SITES in "
                        "ray_tpu/util/failpoints.py"))
            continue
        # -- metric emissions -----------------------------------------
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in _EMIT_METHODS):
            continue
        ref = _family_ref(fn.value, imported)
        if ref is None:
            continue
        attr, via_alias = ref
        family = imported.get(attr, attr)
        scope = _scope_of(node, parents)
        if family not in families:
            findings.append(Finding(
                "CD004", mod.relpath, node.lineno, scope, family,
                f"metric family {family} is not declared in the "
                f"registry (ray_tpu/util/metrics.py) — AttributeError "
                f"at runtime, and the registry-driven grafana "
                f"dashboard can never panel it",
                "declare the family in util/metrics.py (grafana panels "
                "generate from the registry)"))
            continue
        declared = families[family]
        passed = _literal_tag_keys(node, fn.attr)
        if passed is not None and set(passed) != set(declared):
            missing = sorted(set(declared) - set(passed))
            extra = sorted(set(passed) - set(declared))
            parts = []
            if missing:
                parts.append(f"missing {missing} (ValueError at "
                             f"runtime)")
            if extra:
                parts.append(f"extra {extra} (silently dropped by "
                             f"Metric._key — the dimension never "
                             f"reaches the exposition)")
            findings.append(Finding(
                "CD003", mod.relpath, node.lineno, scope,
                f"{family}:{','.join(sorted(passed))}",
                f"emission of {family} with tag keys "
                f"{sorted(passed)} != declared {sorted(declared)}: "
                f"{'; '.join(parts)}",
                "pass exactly the declared tag keys (or change the "
                "declaration and the grafana legend with it)"))
        if is_recorder:
            leaf = scope.rsplit(".", 1)[-1] if scope else scope
            root = scope.split(".", 1)[0]
            if leaf not in recorder_allowed \
                    and root not in recorder_allowed:
                findings.append(Finding(
                    "CD005", mod.relpath, node.lineno, scope, family,
                    f"direct {family} emission in two-sided recorder "
                    f"module outside apply_events/retract_gauges: this "
                    f"observation is never buffered for the "
                    f"worker-events replay, so the cluster backend's "
                    f"federated scrape silently misses it",
                    "route the observation through _emit so both sides "
                    "record"))
    return findings
