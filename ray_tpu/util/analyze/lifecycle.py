"""Pass 10 — lifecycle discipline (LC): what's acquired must be freed.

Three leak classes the PR-11..14 review rounds caught by hand, each
with a structural signature:

* **LC001** (cross-module, full scans) — a *per-entity* gauge family
  (tag keys beyond ``node_id``: worker, rank, trial, pool, deployment,
  device, ...) that some module emits (``set``/``inc``/``dec``) but NO
  module ever retracts (``.remove(``). Dead workers/replicas/ranks
  then stay on the federated scrape forever — the exact drift the
  agent's retraction sweeps exist to prevent. Node-level gauges are
  exempt (their series die with the node's registry).
* **LC002** — a ship-buffer drain whose upload can fail must requeue:
  a function that calls ``drain_events()`` and then performs an RPC
  must reference ``requeue_events`` in an exception path. The
  serve/goodput planes promise exact counts — a chaos-severed channel
  silently dropping a drained batch breaks the cross-check benches.
* **LC003** — a declared acquire/release pair: a line annotated
  ``# slot-guard: <releaser>[,<releaser2>]`` (the engine's decode-slot
  admission, a pool carve-out) requires a ``try`` in the same function
  whose except/finally calls one of the named releasers. If review
  removes the requeue/release edge, the declaration fails loud instead
  of the slot leaking on the failure path.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set

from ray_tpu.util.analyze.core import (
    Finding,
    FindingSink,
    ParsedModule,
    analysis_pass,
    cross_pass,
)
from ray_tpu.util.analyze.resolver import callee_name, receiver_of

_SLOT_GUARD_RE = re.compile(r"#\s*slot-guard:\s*([\w, ]+)")
_EMIT_METHODS = frozenset({"set", "inc", "dec"})
_NODE_LEVEL_TAGS = frozenset({"node_id"})


def _gauge_families() -> Dict[str, tuple]:
    """{family attr name: tag_keys} for registry Gauges with per-entity
    tag dimensions (beyond node_id)."""
    from ray_tpu.util import metrics as m

    out = {}
    for name, inst in vars(m).items():
        if isinstance(inst, m.Gauge):
            extra = set(inst.tag_keys) - _NODE_LEVEL_TAGS
            if extra:
                out[name] = tuple(inst.tag_keys)
    return out


def _family_method_refs(tree: ast.Module, families: Set[str],
                        methods: frozenset) -> Dict[str, int]:
    """{family: first line} where ``<alias>.FAMILY.<method>(...)`` or
    ``FAMILY.<method>(...)`` appears."""
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in methods):
            continue
        base = node.func.value
        fam = None
        if isinstance(base, ast.Attribute) and base.attr.isupper():
            fam = base.attr
        elif isinstance(base, ast.Name) and base.id.isupper():
            fam = base.id
        if fam in families and fam not in out:
            out[fam] = node.lineno
    return out


@cross_pass("lifecycle")
def unretracted_gauge_findings(
        modules: Sequence[ParsedModule]) -> List[Finding]:
    """**LC001** — whole-tree join: per-entity gauge families emitted
    somewhere must be retracted somewhere."""
    families = _gauge_families()
    fam_names = set(families)
    emits: Dict[str, tuple] = {}   # family -> (relpath, line)
    removes: Set[str] = set()
    for mod in modules:
        if mod.relpath.endswith("util/metrics.py"):
            continue  # the registry itself (helpers touch every family)
        for fam, line in _family_method_refs(
                mod.tree, fam_names, _EMIT_METHODS).items():
            emits.setdefault(fam, (mod.relpath, line))
        for fam in _family_method_refs(
                mod.tree, fam_names, frozenset({"remove"})):
            removes.add(fam)
    findings: List[Finding] = []
    for fam in sorted(set(emits) - removes):
        relpath, line = emits[fam]
        tags = families[fam]
        findings.append(Finding(
            "LC001", relpath, line, "<module>", fam,
            f"per-entity gauge family {fam} (tags {list(tags)}) is "
            f"emitted here but no scanned module ever retracts it "
            f"(.remove(...)): dead entities stay on the federated "
            f"scrape forever",
            "add the family to a retraction sweep (the agent's "
            "worker-death / stop path) keyed by the entity tags"))
    return findings


def _fn_calls_named(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and callee_name(node) == name:
            return True
    return False


def _rpc_in(fn: ast.AST) -> Optional[int]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and callee_name(node) in ("call", "call_stream") \
                and receiver_of(node) is not None:
            return node.lineno
    return None


def _references(fn: ast.AST, name: str) -> bool:
    """The function references ``name`` anywhere — the requeue may live
    in an except handler (the classic shape) or on a bounded-resend
    overflow path; total absence is the bug."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and callee_name(node) == name:
            return True
        if isinstance(node, ast.Attribute) and node.attr == name:
            return True
    return False


@analysis_pass("lifecycle")
def lifecycle_pass(mod: ParsedModule) -> List[Finding]:
    sink = FindingSink(mod.relpath)
    if "util/analyze/" in mod.relpath:
        # The analyzer documents its own annotation grammar — those
        # docstring examples are not declarations.
        return sink.findings
    model = mod.model()

    # -- LC002: drain -> upload must requeue on failure -----------------
    for cm, fn, scope in model.functions():
        if not _fn_calls_named(fn, "drain_events"):
            continue
        rpc_line = _rpc_in(fn)
        if rpc_line is None:
            continue  # local consumption (tests, readers): no upload
        if not _references(fn, "requeue_events"):
            sink.emit(
                "LC002", rpc_line, scope, "requeue_events",
                f"{scope} drains a ship buffer and uploads it over RPC "
                f"but never requeues on failure: a severed channel "
                f"silently loses observations the plane promises to "
                f"count exactly",
                "requeue_events(<drained>) on the upload's failure "
                "path (front of the buffer; overflow counts into the "
                "drop counter) — or keep the batch and resend it under "
                "its original dedup seq")

    # -- LC003: declared slot-guard pairs -------------------------------
    guards = {}  # line -> [releaser names]
    for i, text in enumerate(mod.lines, 1):
        m = _SLOT_GUARD_RE.search(text)
        if m:
            guards[i] = [s.strip() for s in m.group(1).split(",")
                         if s.strip()]
    if guards:
        for cm, fn, scope in model.functions():
            start = fn.lineno
            end = getattr(fn, "end_lineno", fn.lineno)
            mine = {ln: names for ln, names in guards.items()
                    if start <= ln <= end}
            if not mine:
                continue
            for ln, names in sorted(mine.items()):
                guards.pop(ln, None)
                ok = any(_handlers_or_finally_call(fn, name)
                         for name in names)
                if not ok:
                    sink.emit(
                        "LC003", ln, scope, ",".join(names),
                        f"slot-guard declares that {' / '.join(names)} "
                        f"releases this acquisition on failure, but no "
                        f"try except/finally in {scope} calls it: the "
                        f"slot leaks on the failure edge",
                        "wrap the post-acquire region in try/except "
                        "(or finally) that calls the declared releaser")
        for ln, names in sorted(guards.items()):
            sink.emit(
                "LC003", ln, "<module>", ",".join(names),
                "slot-guard annotation outside any function: the "
                "declared release pair guards nothing",
                "move the annotation onto the acquiring line inside "
                "the function")
    return sink.findings


def _handlers_or_finally_call(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Try):
            continue
        for body in [h.body for h in node.handlers] + [node.finalbody]:
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) \
                            and callee_name(sub) == name:
                        return True
    return False
