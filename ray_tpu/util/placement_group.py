"""Placement groups: atomic gang reservation of resource bundles.

Reference parity: ``python/ray/util/placement_group.py:128`` (user API) and
the GCS placement-group manager's 2-phase commit across raylets
(``src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h:265``) with the
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD bundle-packing policies
(``src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h``).

TPU extension (SURVEY.md §7): strategy ``"STRICT_SPREAD"`` over TPU hosts is
how a training job reserves one whole slice host per worker; the cluster
backend's scheduler understands ``TPU`` bundles as ICI-contiguous chip
claims on a host.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu._private import worker as _worker

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


@dataclass
class PlacementGroup:
    """Handle to a (possibly still-pending) placement group."""

    id: str
    bundles: List[Dict[str, float]] = field(default_factory=list)
    strategy: str = "PACK"
    name: str = ""

    def ready(self):
        """ObjectRef that resolves (to this PG's id) once all bundles are
        reserved — awaitable with ray_tpu.get, like the reference's
        ``PlacementGroup.ready()``."""
        return _worker.backend().placement_group_ready(self.id)

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout_seconds
        while time.monotonic() < deadline:
            state = _worker.backend().placement_group_table(self.id)
            if state and state["state"] == "CREATED":
                return True
            if state and state["state"] == "INFEASIBLE":
                return False
            time.sleep(0.01)
        return False

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        return self.bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: str = "",
    lifetime: Optional[str] = None,
    spot: bool = True,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not isinstance(b, dict) or not b:
            raise ValueError(f"each bundle must be a non-empty dict, got {b!r}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"bundle resources must be >= 0: {b!r}")
    pg_id = _worker.backend().create_placement_group(
        [dict(b) for b in bundles], strategy, name, lifetime, spot=spot
    )
    return PlacementGroup(pg_id, [dict(b) for b in bundles], strategy, name)


def remove_placement_group(pg: PlacementGroup) -> None:
    _worker.backend().remove_placement_group(pg.id)


def placement_group_table(pg: Optional[PlacementGroup] = None):
    """State of one PG (dict) or all PGs (dict of dicts)."""
    return _worker.backend().placement_group_table(pg.id if pg else None)


def get_current_placement_group() -> Optional[PlacementGroup]:
    """The PG capturing the current task/actor, if any (set by the runtime
    when a task runs with capture_child_tasks)."""
    info = _worker.backend().current_placement_group()
    if info is None:
        return None
    return PlacementGroup(info["id"], info["bundles"], info["strategy"], info["name"])
