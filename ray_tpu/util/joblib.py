"""joblib backend over tasks (reference: ``python/ray/util/joblib/``).

``register_ray_tpu()`` installs a ``ray_tpu`` joblib backend so existing
scikit-learn-style code parallelizes over the cluster unchanged:

    from ray_tpu.util.joblib import register_ray_tpu
    register_ray_tpu()
    with joblib.parallel_backend("ray_tpu"):
        joblib.Parallel()(joblib.delayed(f)(x) for x in data)

Each joblib batch (a callable of pre-batched work items) becomes one task;
``effective_n_jobs`` reports the cluster's CPU count so joblib sizes its
batches for the whole cluster, not one host.
"""

from __future__ import annotations

from joblib.parallel import AutoBatchingMixin, ParallelBackendBase

import ray_tpu


class _TaskBatchResult:
    """Future-like wrapper joblib polls via ``get``."""

    def __init__(self, ref, timeout: float | None):
        self._ref = ref
        self._timeout = timeout

    def get(self, timeout=None):
        return ray_tpu.get(self._ref, timeout=timeout or self._timeout)


class RayTpuBackend(AutoBatchingMixin, ParallelBackendBase):
    """One task per joblib batch; results stream back through the object
    store (reference ``util/joblib/ray_backend.py`` shape)."""

    supports_timeout = True
    # joblib >= 1.3 probes this to decide whether to pass inner_n_jobs
    supports_inner_max_num_threads = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._remote_batch = None

    def effective_n_jobs(self, n_jobs: int) -> int:
        if not ray_tpu.is_initialized():
            return 1
        cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        if n_jobs == -1:
            return max(1, cpus)
        return max(1, min(n_jobs, cpus)) if n_jobs else 1

    def configure(self, n_jobs: int = 1, parallel=None, **kwargs) -> int:
        n = self.effective_n_jobs(n_jobs)
        self.parallel = parallel
        self._remote_batch = ray_tpu.remote(_run_joblib_batch)
        return n

    def submit(self, func, callback=None):
        """joblib >= 1.3 entry point; older releases call apply_async."""
        return self.apply_async(func, callback)

    def apply_async(self, func, callback=None):
        ref = self._remote_batch.remote(func)
        result = _TaskBatchResult(ref, timeout=None)
        if callback is not None:
            # joblib's callback just schedules the next batch; resolving in
            # a daemon thread keeps submission pipelined like the
            # reference's actor-pool backend.
            import threading

            def waiter():
                try:
                    result.get()
                finally:
                    callback(result)

            threading.Thread(target=waiter, daemon=True).start()
        return result

    def abort_everything(self, ensure_ready: bool = True):
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)


def _run_joblib_batch(batch):
    return batch()


def register_ray_tpu() -> None:
    """Register the backend under the name ``"ray_tpu"``."""
    from joblib import register_parallel_backend

    register_parallel_backend("ray_tpu", RayTpuBackend)
