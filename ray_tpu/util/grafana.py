"""Grafana dashboard generation from the metrics registry.

Reference parity:
``dashboard/modules/metrics/grafana_dashboard_factory.py`` — the
reference ships generated Grafana dashboard JSON wired to its Prometheus
metrics; here the dashboard is generated FROM the live metric registry,
so every registered Counter/Gauge/Histogram gets a panel whose query
matches exactly what this repo's exporter emits (names verbatim — no
implicit ``_total`` suffixing; see ``util/metrics.py`` exposition).

    from ray_tpu.util.grafana import generate_dashboard, write_dashboard
    write_dashboard("grafana/ray_tpu_dashboard.json")

Import the JSON into Grafana with a Prometheus data source scraping the
cluster's ``/metrics`` endpoints (``ray_tpu.util.metrics
.start_metrics_server``).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

from ray_tpu.util import metrics as _metrics


def _panel(panel_id: int, title: str, expr: str, unit: str = "short",
           x: int = 0, y: int = 0,
           legend: str = "{{instance}}") -> dict:
    return {
        "id": panel_id,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [{
            "expr": expr,
            "legendFormat": legend,
            "refId": "A",
        }],
    }


def _unit_of(name: str) -> str:
    """Grafana unit inferred from the prometheus naming convention."""
    if name.endswith("_bytes") or "_bytes_" in name:
        return "bytes"
    if name.endswith(("_seconds", "_seconds_total")):
        return "s"
    if name.endswith("_percent"):
        return "percent"
    return "short"


def _legend_of(m: "_metrics.Metric") -> str:
    """Series legend from the metric's OWN tag keys (a registry-driven
    dashboard must label by what the exporter actually tags, not a
    hardcoded {{instance}})."""
    if not m.tag_keys:
        return "{{instance}}"
    return " ".join("{{" + k + "}}" for k in m.tag_keys)


def _registry_panels() -> List[tuple]:
    """(title, expr, unit, legend) per registered metric — derived from
    the live registry, so new families (device gauges, phase
    histograms, ...) get panels without touching this module."""
    panels = []
    for m in _metrics.registered():
        name = m.name
        legend = _legend_of(m)
        if isinstance(m, _metrics.Counter):
            # The exporter emits the registered name VERBATIM (callers
            # who want the prometheus _total convention put it in the
            # name) — query exactly that.
            expr = f"rate({name}[1m])"
            title = f"{name} /s"
        elif isinstance(m, _metrics.Histogram):
            expr = (f"histogram_quantile(0.99, "
                    f"rate({name}_bucket[5m]))")
            title = f"{name} p99"
            if m.tag_keys:
                legend = _legend_of(m) + " p99"
        else:  # Gauge
            expr = name
            title = name
        if m.description:
            title = f"{title} — {m.description}"
        panels.append((title, expr, _unit_of(name), legend))
    return panels


def generate_dashboard(title: str = "ray_tpu cluster",
                       include_registry: bool = True) -> dict:
    """Grafana v10 dashboard JSON: one panel per registered metric
    (rate for counters, p99 for histograms, value for gauges)."""
    entries: List[tuple] = []
    if include_registry:
        entries += _registry_panels()
    panels = []
    for i, (ptitle, expr, unit, legend) in enumerate(entries):
        panels.append(_panel(
            i + 1, ptitle, expr, unit,
            x=(i % 2) * 12, y=(i // 2) * 8,
            legend=legend,
        ))
    return {
        "title": title,
        "uid": "ray-tpu-default",
        "schemaVersion": 39,
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def write_dashboard(path: str, title: str = "ray_tpu cluster",
                    include_registry: bool = True) -> str:
    """Write the generated dashboard JSON; returns the path (the
    reference's dashboard factory writes into the session dir the same
    way)."""
    dash = generate_dashboard(title, include_registry)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dash, f, indent=1)
    os.replace(tmp, path)
    return path
