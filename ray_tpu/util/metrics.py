"""User-defined metrics: Counter / Gauge / Histogram + Prometheus text.

Reference parity: ``python/ray/util/metrics.py`` (the user API) and the
Prometheus exposition of ``_private/prometheus_exporter.py``; the OpenCensus
agent pipeline collapses to an in-process registry with a text endpoint.
"""

from __future__ import annotations

import re as _re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: "List[Metric]" = []

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
]


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self.tag_keys) - set(merged)
        if missing:
            raise ValueError(f"metric {self.name} missing tags {missing}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> bool:
        """Drop one tagged series (e.g. a dead worker's gauges) so the
        exposition doesn't accumulate stale children forever. Returns
        whether the series existed."""
        key = self._key(tags)
        removed = False
        with self._lock:
            for table in ("_values", "_counts", "_sums", "_totals"):
                d = getattr(self, table, None)
                if d is not None and d.pop(key, None) is not None:
                    removed = True
        return removed

    def series(self) -> List[Dict[str, str]]:
        """Tag dicts of every live child. Lifecycle sweeps (e.g. a
        trial stopping) enumerate these to retract an entity's series
        without knowing every key the entity ever emitted."""
        keys: List[Tuple] = []
        with self._lock:
            for table in ("_values", "_counts", "_sums", "_totals"):
                d = getattr(self, table, None)
                if d is not None:
                    keys.extend(d.keys())
        return [dict(zip(self.tag_keys, k))
                for k in dict.fromkeys(keys)]

    def _fmt_tags(self, key: Tuple) -> str:
        if not self.tag_keys:
            return ""
        inner = ",".join(
            f'{k}="{v}"' for k, v in zip(self.tag_keys, key)
        )
        return "{" + inner + "}"

    def expose(self) -> List[str]:
        raise NotImplementedError


class Counter(Metric):
    metric_type = "counter"

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{self._fmt_tags(key)} {v}")
        return out


class Gauge(Metric):
    metric_type = "gauge"

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None):
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, tags=None):
        self.inc(-value, tags)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{self._fmt_tags(key)} {v}")
        return out


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        super().__init__(name, description, tag_keys)
        self.boundaries = list(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            import bisect

            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                base_tags = list(zip(self.tag_keys, key))
                cumulative = 0
                for bound, c in zip(self.boundaries, counts):
                    cumulative += c
                    tags = base_tags + [("le", str(bound))]
                    inner = ",".join(f'{k}="{v}"' for k, v in tags)
                    out.append(f"{self.name}_bucket{{{inner}}} {cumulative}")
                cumulative += counts[-1]
                inner = ",".join(
                    f'{k}="{v}"' for k, v in base_tags + [("le", "+Inf")]
                )
                out.append(f"{self.name}_bucket{{{inner}}} {cumulative}")
                out.append(
                    f"{self.name}_sum{self._fmt_tags(key)} {self._sums[key]}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_tags(key)} {self._totals[key]}"
                )
        return out


# -- node reporter gauges (reference: dashboard/modules/reporter's
# per-worker cpu/mem stats flowing into the Prometheus exporter). The
# node agent's telemetry loop samples /proc for each worker process and
# sets these; a process that runs no agent just exposes the empty
# families. Tagged per worker so one scrape shows the whole node.
WORKER_CPU_PERCENT = Gauge(
    "ray_tpu_worker_cpu_percent",
    "CPU utilization of a worker process (percent of one core)",
    tag_keys=("node_id", "worker_id", "pid"),
)
WORKER_RSS_BYTES = Gauge(
    "ray_tpu_worker_rss_bytes",
    "Resident set size of a worker process in bytes",
    tag_keys=("node_id", "worker_id", "pid"),
)
WORKER_UPTIME_SECONDS = Gauge(
    "ray_tpu_worker_uptime_seconds",
    "Seconds since the worker process was spawned",
    tag_keys=("node_id", "worker_id", "pid"),
)
NODE_WORKER_COUNT = Gauge(
    "ray_tpu_node_worker_count",
    "Live worker processes on a node",
    tag_keys=("node_id",),
)

# -- task execution phases (fed by the agents from the workers' batched
# task-event reports: each finished task carries wall-ns per phase —
# arg fetch/deserialize, execute, output serialize+store — so p50/p99
# per phase is scrapeable without the state API).
TASK_PHASE_SECONDS = Histogram(
    "ray_tpu_task_phase_seconds",
    "Wall time of one task execution phase (get_args/execute/put_outputs)",
    boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0],
    # node_id like every per-node family: on a real multi-host cluster
    # each agent has its OWN registry, and a phase-only label set would
    # federate as duplicate series (Prometheus rejects the scrape).
    tag_keys=("node_id", "phase"),
)

# -- JAX/XLA device telemetry (util/device_telemetry.py snapshots,
# sampled per worker process and exported by its node agent; stubbed —
# device count 0, no per-device children — when jax never loads).
DEVICE_COUNT = Gauge(
    "ray_tpu_device_count",
    "Accelerator devices visible on a node (0 = no jax-loaded process)",
    tag_keys=("node_id",),
)
DEVICE_MEM_IN_USE = Gauge(
    "ray_tpu_device_memory_bytes_in_use",
    "Device (HBM) bytes in use by a worker process, per device",
    tag_keys=("node_id", "worker_id", "device"),
)
DEVICE_MEM_PEAK = Gauge(
    "ray_tpu_device_memory_peak_bytes",
    "Peak device (HBM) bytes in use by a worker process, per device",
    tag_keys=("node_id", "worker_id", "device"),
)
DEVICE_MEM_LIMIT = Gauge(
    "ray_tpu_device_memory_bytes_limit",
    "Device (HBM) byte capacity visible to a worker process, per device",
    tag_keys=("node_id", "worker_id", "device"),
)
DEVICE_JAX_COMPILES = Gauge(
    "ray_tpu_device_jax_compiles",
    "Cumulative XLA backend compiles in a worker process",
    tag_keys=("node_id", "worker_id"),
)
DEVICE_JAX_COMPILE_SECONDS = Gauge(
    "ray_tpu_device_jax_compile_seconds",
    "Cumulative XLA backend compile wall seconds in a worker process",
    tag_keys=("node_id", "worker_id"),
)
DEVICE_JAX_CACHE_HITS = Gauge(
    "ray_tpu_device_jax_cache_hits",
    "Cumulative JAX compilation-cache hits in a worker process",
    tag_keys=("node_id", "worker_id"),
)
DEVICE_JAX_CACHE_MISSES = Gauge(
    "ray_tpu_device_jax_cache_misses",
    "Cumulative JAX compilation-cache misses in a worker process",
    tag_keys=("node_id", "worker_id"),
)

# -- node drain lifecycle (head-side; the drain coordinator records one
# increment per initiated drain and the wall time from DRAINING to
# deregistration, so preemption churn is visible per reason).
NODE_DRAINS_TOTAL = Counter(
    "ray_tpu_node_drains_total",
    "Node drains initiated, by reason (preemption, autoscaler_idle, ...)",
    tag_keys=("reason",),
)
NODE_DRAIN_DURATION_SECONDS = Histogram(
    "ray_tpu_node_drain_duration_seconds",
    "Wall time from drain start to node deregistration",
    boundaries=[0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0],
    tag_keys=("reason",),
)
NODE_DRAIN_ACTORS_MIGRATED = Counter(
    "ray_tpu_node_drain_actors_migrated_total",
    "Actors proactively reconstructed off draining nodes",
    tag_keys=("reason",),
)
# -- placement-group rescheduling (head-side; the gang-migration half of
# the drain/preemption plane: one increment per completed bundle
# migration, and the wall time from losing a bundle's node to the
# reservation being whole again on healthy nodes).
PG_RESCHEDULES_TOTAL = Counter(
    "ray_tpu_pg_reschedules_total",
    "Completed placement-group reschedules, by trigger cause "
    "(drain = planned departure, node_death = crash-detected loss)",
    tag_keys=("cause",),
)
PG_RESCHEDULE_SECONDS = Histogram(
    "ray_tpu_pg_reschedule_seconds",
    "Wall time from a gang bundle losing its node to the group's "
    "reservation being CREATED again on healthy nodes",
    boundaries=[0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                120.0],
)

# -- fleet autoscaler (execution half, round 17): per-node-type launch /
# failure / quarantine / scale-down counters plus the pending-demand
# gauge the bin-packer planned against — `ray-tpu top` reads these from
# the signal ring as fleet churn. The pending-demand gauge is per-kind
# (task/actor/pg_bundle/slo_burn) and retracted on autoscaler stop so a
# torn-down fleet doesn't linger on the federated scrape.
AUTOSCALER_LAUNCHES_TOTAL = Counter(
    "ray_tpu_autoscaler_launches_total",
    "Provider nodes successfully launched, by node type",
    tag_keys=("node_type",),
)
AUTOSCALER_LAUNCH_FAILURES_TOTAL = Counter(
    "ray_tpu_autoscaler_launch_failures_total",
    "Provider create_node failures/timeouts, by node type",
    tag_keys=("node_type",),
)
AUTOSCALER_QUARANTINES_TOTAL = Counter(
    "ray_tpu_autoscaler_quarantines_total",
    "Node types benched after consecutive boot failures",
    tag_keys=("node_type",),
)
AUTOSCALER_SCALE_DOWNS_TOTAL = Counter(
    "ray_tpu_autoscaler_scale_downs_total",
    "Provider nodes terminated by scale-down (drained first unless the "
    "head was unreachable), by node type",
    tag_keys=("node_type",),
)
AUTOSCALER_LAUNCH_SECONDS = Histogram(
    "ray_tpu_autoscaler_launch_seconds",
    "Wall time of one successful provider create_node call",
    boundaries=[0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 120.0,
                300.0],
    tag_keys=("node_type",),
)
AUTOSCALER_PENDING_DEMAND = Gauge(
    "ray_tpu_autoscaler_pending_demand",
    "Pending demand entries the bin-packer planned against, by kind "
    "(task, actor, pg_bundle, slo_burn)",
    tag_keys=("kind",),
)

# -- head control plane (head-side; the contention instrumentation the
# 100k-task/1k-actor envelope reads: per-method handler latency on the
# head's RPC server, time spent WAITING on each head lock shard — an
# uncontended acquire observes nothing — and the write-behind
# persistence queue, so "the head is melting" shows up in the federated
# scrape as a named shard/method instead of a vibe).
HEAD_RPC_SECONDS = Histogram(
    "ray_tpu_head_rpc_seconds",
    "Head RPC handler wall time, per method",
    boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5],
    tag_keys=("method",),
)
HEAD_LOCK_WAIT_SECONDS = Histogram(
    "ray_tpu_head_lock_wait_seconds",
    "Time head threads spent blocked acquiring a contended lock shard "
    "(nodes = node/actor/PG tables, objects = object/ref tables, "
    "events = spans/logs)",
    boundaries=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0],
    tag_keys=("shard",),
)
HEAD_PERSIST_QUEUE_DEPTH = Gauge(
    "ray_tpu_head_persist_queue_depth",
    "Dirty keys waiting in the head's write-behind persistence queue",
)
HEAD_PERSIST_FLUSH_SECONDS = Histogram(
    "ray_tpu_head_persist_flush_seconds",
    "Wall time of one write-behind sqlite batch transaction",
    boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 1.0],
)
HEAD_PERSIST_COALESCED = Counter(
    "ray_tpu_head_persist_coalesced_total",
    "Per-key writes absorbed by the write-behind queue before a flush "
    "(each one was a synchronous fsync'd transaction before round 6)",
)
HEAD_SPANS_DROPPED = Counter(
    "ray_tpu_head_spans_dropped_total",
    "Tracing spans dropped by the head's bounded span ring",
)
TRACING_DROPPED_SPANS = Counter(
    "ray_tpu_tracing_dropped_spans_total",
    "Finished spans a process dropped to its in-memory ring cap before "
    "they could be drained (worker-side drops are re-attributed to "
    "their node by the agent when the event batch ships the count)",
    tag_keys=("node_id",),
)
HEAD_TRACES_DROPPED = Counter(
    "ray_tpu_head_traces_dropped_total",
    "Assembled traces evicted from the head's bounded trace store, "
    "by cause (sampled = tail-sampling declined, evicted = retention "
    "cap, span_cap = per-trace span limit clipped spans)",
    tag_keys=("cause",),
)
TASK_RECORDS_EVICTED = Counter(
    "ray_tpu_task_records_evicted_total",
    "Finished task records evicted from a node agent's bounded ring",
    tag_keys=("node_id",),
)
PUBSUB_COALESCED = Counter(
    "ray_tpu_pubsub_coalesced_total",
    "Pubsub messages absorbed by per-(subscriber,channel,key) "
    "coalescing (subscriber saw latest state instead of history)",
)
PUBSUB_DROPPED = Counter(
    "ray_tpu_pubsub_dropped_total",
    "Pubsub messages dropped on slow-subscriber buffer overflow",
)

# -- Serve request path (the SLO latency plane: replicas, routers and
# batch queues record into ray_tpu/serve/_observability.py, which ships
# the observations over the worker-events plane so they land in the
# scraped (agent) registry; per-replica gauge children are retracted
# when the replica's worker dies, same lifecycle as the /proc gauges).
# Every family is node_id-tagged: on a real multi-host cluster each
# agent has its own registry and a deployment-only label set would
# federate as duplicate series.
SERVE_LATENCY_BOUNDARIES = [
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0,
]
SERVE_REQUEST_SECONDS = Histogram(
    "ray_tpu_serve_request_seconds",
    "Serve request wall time per phase (route=router assign, "
    "queue_wait=assign to replica execution, batch_wait=time queued in "
    "a @serve.batch queue, execute=user callable, serialize=response "
    "serialize/transfer remainder, total=end to end)",
    boundaries=SERVE_LATENCY_BOUNDARIES,
    tag_keys=("node_id", "deployment", "phase"),
)
SERVE_REQUESTS_TOTAL = Counter(
    "ray_tpu_serve_requests_total",
    "Serve requests by terminal status (ok/error/shed), counted once "
    "at the router",
    tag_keys=("node_id", "deployment", "status"),
)
SERVE_SHED_TOTAL = Counter(
    "ray_tpu_serve_shed_total",
    "Deadline-expired serve requests shed instead of executed, by the "
    "site that shed them (router/replica/batch)",
    tag_keys=("node_id", "deployment", "reason"),
)
SERVE_REPLICA_ONGOING = Gauge(
    "ray_tpu_serve_replica_ongoing",
    "In-flight requests executing on one serve replica",
    tag_keys=("node_id", "deployment", "replica"),
)
SERVE_ROUTER_QUEUE_DEPTH = Gauge(
    "ray_tpu_serve_router_queue_depth",
    "Requests blocked in a router process waiting for replica capacity "
    "(backpressure behind max_concurrent_queries)",
    tag_keys=("node_id", "deployment", "worker"),
)
SERVE_BATCH_SIZE = Histogram(
    "ray_tpu_serve_batch_size",
    "Items per executed @serve.batch batch",
    boundaries=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0],
    tag_keys=("node_id", "deployment"),
)
SERVE_RECONCILE_SECONDS = Gauge(
    "ray_tpu_serve_reconcile_seconds",
    "Duration of the serve controller's last reconcile pass (health "
    "probes + autoscaling + replica convergence)",
    tag_keys=("node_id",),
)
SERVE_EVENTS_DROPPED = Counter(
    "ray_tpu_serve_events_dropped_total",
    "Serve observations discarded by a worker's bounded ship buffer "
    "before the event flusher drained them (server-side request "
    "counts undercount by this much — no silent caps)",
    tag_keys=("node_id",),
)

# -- continuous-batching LLM decode engine (serve/llm_engine.py): one
# compiled decode step over a fixed slot batch, requests admitted
# between steps. Recorded through the same two-sided serve recorder
# (engine replicas are workers; events replay into the agent registry
# and federate on /metrics/cluster). Read batch occupancy BEFORE
# blaming step latency: a slow tokens/s with full occupancy is a
# kernel problem, with empty occupancy an admission problem.
SERVE_DECODE_STEP_SECONDS = Histogram(
    "ray_tpu_serve_decode_step_seconds",
    "Wall time of one compiled decode iteration of the LLM engine "
    "(device step + host sampling sync)",
    boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0],
    tag_keys=("node_id", "deployment"),
)
SERVE_DECODE_BATCH_OCCUPANCY = Histogram(
    "ray_tpu_serve_decode_batch_occupancy",
    "Active slots per decode iteration (the continuous-batching "
    "utilization signal: 0-occupancy steps never run; a full batch at "
    "max_batch means admission is the bottleneck)",
    boundaries=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0],
    tag_keys=("node_id", "deployment"),
)
SERVE_DECODE_TTFT_SECONDS = Histogram(
    "ray_tpu_serve_decode_ttft_seconds",
    "Time to first token per admitted stream (submit -> first token "
    "available for delivery, engine-side). Extends past the request "
    "boundaries: under deep admission queues (10k streams on 64 "
    "slots) TTFT IS the queue, minutes not millis",
    boundaries=SERVE_LATENCY_BOUNDARIES + [120.0, 300.0, 600.0],
    tag_keys=("node_id", "deployment"),
)
SERVE_DECODE_TOKENS_TOTAL = Counter(
    "ray_tpu_serve_decode_tokens_total",
    "Tokens produced by the LLM decode engine (prefill first tokens + "
    "decode-step tokens, all streams)",
    tag_keys=("node_id", "deployment"),
)
SERVE_DECODE_ITL_SECONDS = Histogram(
    "ray_tpu_serve_decode_itl_seconds",
    "Inter-token latency (TPOT) per decode-step token: wall time from "
    "a stream's previous token to this one, engine-side (the decode "
    "half of the TTFT/TPOT SLO pair — a full batch with climbing ITL "
    "is a step-latency problem, not an admission problem)",
    boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5],
    tag_keys=("node_id", "deployment"),
)

# -- signal plane (head metrics history ring + SLO burn-rate layer,
# cluster/signals.py): the head self-scrapes its own federated
# /metrics/cluster into a bounded time-series ring and answers windowed
# queries from history — these families are the plane's SELF-overhead
# accounting (the TPU-concurrency-limits lesson: host-side sensing is a
# first-order cost, so the sensor charges itself on the same scrape it
# feeds) plus the SLO layer's exported burn state.
HEAD_SIGNAL_SCRAPE_SECONDS = Histogram(
    "ray_tpu_head_signal_scrape_seconds",
    "Wall time of one head signal-plane self-scrape (federated "
    "cluster_metrics_text render + parse + ring ingest)",
    boundaries=[0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5],
)
HEAD_SIGNAL_SERIES = Gauge(
    "ray_tpu_head_signal_series",
    "Distinct time series retained in the head's signal-plane history "
    "ring (bounded by signal_max_series)",
)
HEAD_SIGNAL_EVICTIONS_TOTAL = Counter(
    "ray_tpu_head_signal_evictions_total",
    "Series evicted from the head's signal-plane ring, by reason "
    "(series_cap = ring full at signal_max_series, dead_node = node "
    "died, stale = series stopped reporting for a full history window)",
    tag_keys=("reason",),
)
AGENT_METRICS_RENDER_SECONDS = Gauge(
    "ray_tpu_agent_metrics_render_seconds",
    "Wall seconds the node agent spent rendering its previous "
    "metrics_text response (the per-node sensing cost every federated "
    "scrape fan-out pays; one scrape behind by construction — the "
    "cost isn't known until the body is rendered)",
    tag_keys=("node_id",),
)
SLO_STATE = Gauge(
    "ray_tpu_slo_state",
    "Burn-rate state of a registered SLO (0=ok 1=warning 2=burning)",
    tag_keys=("slo",),
)
SLO_VALUE = Gauge(
    "ray_tpu_slo_value",
    "Most recent windowed value of a registered SLO's signal",
    tag_keys=("slo",),
)
SLO_THRESHOLD = Gauge(
    "ray_tpu_slo_threshold",
    "Configured threshold of a registered SLO",
    tag_keys=("slo",),
)

# -- training goodput plane (input-pipeline + per-step train telemetry:
# dataset stages, consumer-loop stall accounting, session-driven step
# phases, the per-rank straggler gauge, and the trainer's downtime
# ledger — recorded two-sided through ray_tpu/train/_observability.py,
# the serve-plane shape: local registry immediately + worker-events
# replay into the agent registry the federated scrape sees; per-rank
# gauge children are retracted when the worker dies). node_id-tagged
# like every per-node family so multi-host federation never duplicates
# series.
DATA_STAGE_SECONDS = Histogram(
    "ray_tpu_data_stage_seconds",
    "Wall time of one executed dataset stage (driver-observed, whole "
    "stage across its blocks)",
    boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0,
                60.0, 300.0],
    tag_keys=("node_id", "stage"),
)
DATA_BLOCK_SECONDS = Histogram(
    "ray_tpu_data_block_seconds",
    "Wall time of one block through one dataset stage (task-measured)",
    boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                30.0],
    tag_keys=("node_id", "stage"),
)
DATA_BLOCK_ROWS = Histogram(
    "ray_tpu_data_block_rows",
    "Rows per output block of a dataset stage (skew shows up here)",
    boundaries=[1.0, 16.0, 64.0, 256.0, 1024.0, 4096.0, 16384.0,
                65536.0, 262144.0, 1048576.0],
    tag_keys=("node_id", "stage"),
)
DATA_BLOCK_BYTES = Histogram(
    "ray_tpu_data_block_bytes",
    "Bytes per output block of a dataset stage (a 10-GiB skewed block "
    "shows up here before it OOMs the store)",
    boundaries=[1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10],
    tag_keys=("node_id", "stage"),
)
DATA_ITER_SECONDS = Histogram(
    "ray_tpu_data_iter_seconds",
    "Consumer-loop time per batch by phase (wait=consumer starved for "
    "the next batch, user=consumer's own time between batches, "
    "transfer=host->device dispatch in iter_device_batches); the "
    "derived stall fraction is wait.sum / (wait.sum + user.sum)",
    boundaries=[0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0],
    tag_keys=("node_id", "phase"),
)
DATA_PREFETCH_OCCUPANCY = Histogram(
    "ray_tpu_data_prefetch_occupancy",
    "Prefetch-buffer occupancy observed as the consumer takes each "
    "block batch (0 = the producer never gets ahead: every batch "
    "starves)",
    boundaries=[0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0],
    tag_keys=("node_id",),
)
TRAIN_STEP_PHASE_SECONDS = Histogram(
    "ray_tpu_train_step_phase_seconds",
    "Wall time of one training-step phase per reported step (data_wait "
    "/ step / report / checkpoint_save / checkpoint_restore), driven "
    "from the session API",
    boundaries=[0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0],
    tag_keys=("node_id", "trial", "phase"),
)
TRAIN_RANK_STEP_SECONDS = Gauge(
    "ray_tpu_train_rank_step_seconds",
    "Most recent step compute seconds per rank of a gang (the "
    "straggler gauge: rank skew at a glance); retracted when the "
    "worker dies",
    tag_keys=("node_id", "trial", "rank"),
)
TRAIN_REPORTS_TOTAL = Counter(
    "ray_tpu_train_reports_total",
    "session.report calls per trial (all ranks)",
    tag_keys=("node_id", "trial"),
)
TRAIN_DOWNTIME_SECONDS = Counter(
    "ray_tpu_train_downtime_seconds_total",
    "Non-productive trial wall seconds attributed by the trainer's "
    "downtime ledger (cause: drain:<reason> / preemption / failure)",
    tag_keys=("node_id", "trial", "cause"),
)
TRAIN_EVENTS_DROPPED = Counter(
    "ray_tpu_train_events_dropped_total",
    "Goodput observations discarded by a worker's bounded ship buffer "
    "before the event flusher drained them (no silent caps)",
    tag_keys=("node_id",),
)

# -- step anatomy plane (round 19: MFU accounting + per-rank phase
# decomposition). Both are per-entity gauges: retracted on worker
# death and session stop via goodput.retract_gauges / retract_trial.
TRAIN_MFU_PERCENT = Gauge(
    "ray_tpu_mfu_percent",
    "Model-FLOPs utilization per rank: XLA cost-model FLOPs per step "
    "(util/xla_cost, from the compiled HLO — not a hand formula) over "
    "measured device-compute seconds, against the measure.py per-chip "
    "peak; retracted on worker death and session stop",
    tag_keys=("node_id", "trial", "rank"),
)
TRAIN_STEP_ANATOMY_SECONDS = Gauge(
    "ray_tpu_step_phase_seconds",
    "Most recent step-anatomy decomposition per rank: data_wait / host "
    "(dispatch until device launch) / compute (synced device wall) / "
    "sync (barrier skew: this rank's wait for the slowest rank); the "
    "four phases partition the instrumented step wall exactly; "
    "retracted on worker death and session stop",
    tag_keys=("node_id", "trial", "phase", "rank"),
)

# -- streaming dataflow (round 14: memory-safe data plane). Block
# splits and pool scaling record two-sided through util/goodput.py
# (tasks/drivers emit events, agents replay them into the federated
# registry); spill traffic records agent-side directly — the agent IS
# the scraped registry for its node.
DATA_BLOCK_SPLITS = Counter(
    "ray_tpu_block_splits_total",
    "Extra output blocks produced by dynamic block splitting (a stage "
    "whose output exceeded target_block_size_bytes; N splits = N "
    "store-friendly objects instead of one oversized block)",
    tag_keys=("node_id", "stage"),
)
DATA_POOL_SIZE = Gauge(
    "ray_tpu_data_pool_size",
    "Live actors in an autoscaling dataset actor pool "
    "(ActorPoolStrategy(min, max): grows on queue depth, shrinks on "
    "idle)",
    tag_keys=("node_id", "pool"),
)
DATA_POOL_QUEUE_DEPTH = Gauge(
    "ray_tpu_data_pool_queue_depth",
    "Blocks queued behind an autoscaling dataset actor pool (the "
    "scale-up pressure signal, sampled at scale decisions)",
    tag_keys=("node_id", "pool"),
)

# -- RPC plane (client-side; one increment per reconnect attempt a
# retry-windowed call makes after losing its connection — a reconnect
# storm against one peer is visible on the federated scrape).
RPC_RECONNECTS_TOTAL = Counter(
    "ray_tpu_rpc_reconnects_total",
    "RPC reconnect attempts after connection loss, by peer address",
    tag_keys=("peer",),
)

# -- daemon-loop survivability (every forever-loop's survival handler
# ticks this when it swallows an exception and re-enters the iteration;
# the DL002 static rule enforces the discipline. A loop stuck in a
# crash-restart cycle shows as a climbing series instead of silently
# burning a core; components retract their loop children on stop so a
# dead node's loops leave the federated scrape).
LOOP_RESTARTS_TOTAL = Counter(
    "ray_tpu_loop_restarts_total",
    "Exceptions a daemon loop survived (swallowed and re-entered the "
    "iteration), by loop name",
    tag_keys=("loop",),
)


def count_loop_restart(loop: str) -> None:
    """One survived daemon-loop exception. Never raises: the survival
    handler calling this is the last line of defense for its loop, and
    a metrics failure must not become the exception that kills it."""
    try:
        LOOP_RESTARTS_TOTAL.inc(tags={"loop": loop})
    except Exception:
        pass


def retract_loop_series(loops: Sequence[str]) -> None:
    """Drop the loop-restart children a stopping component owns (agent
    stop, engine shutdown) so dead nodes' loops vanish from the
    federated scrape. Never raises (stop paths call it)."""
    for loop in loops:
        try:
            LOOP_RESTARTS_TOTAL.remove(tags={"loop": loop})
        except Exception:
            pass

# -- object store / memory observability (agent-side per-node occupancy
# sampled from the shm store's native stats; the head observes object
# lifetimes into the age histogram as the ref-counter frees them, and
# OOM kills count where they happen — on the killing node's agent).
OBJECT_STORE_BYTES_USED = Gauge(
    "ray_tpu_object_store_bytes_used",
    "Bytes resident in a node's shared-memory object store",
    tag_keys=("node_id",),
)
OBJECT_STORE_BYTES_CAPACITY = Gauge(
    "ray_tpu_object_store_bytes_capacity",
    "Byte capacity of a node's shared-memory object store",
    tag_keys=("node_id",),
)
OBJECT_STORE_OBJECTS = Gauge(
    "ray_tpu_object_store_objects",
    "Objects resident in a node's shared-memory object store",
    tag_keys=("node_id",),
)
OBJECT_STORE_EVICTIONS = Counter(
    "ray_tpu_object_store_evictions_total",
    "Objects evicted from a node's object store (LRU or spill-evict)",
    tag_keys=("node_id",),
)
OBJECT_SPILL_DENIED = Counter(
    "ray_tpu_object_spill_denied_total",
    "Spill requests that could not free the requested bytes "
    "(everything left referenced or pinned — a put is about to fail)",
    tag_keys=("node_id",),
)
SPILL_BYTES_TOTAL = Counter(
    "ray_tpu_spill_bytes_total",
    "Bytes written to the node's spill target (local session dir or "
    "the configured spill_uri backend) under memory pressure",
    tag_keys=("node_id",),
)
SPILL_RESTORES_TOTAL = Counter(
    "ray_tpu_spill_restores_total",
    "Spilled objects restored into a node's store (local spill-file "
    "reads plus restore-from-URI recoveries of a dead node's objects)",
    tag_keys=("node_id",),
)
SHM_SWEPT_BYTES = Counter(
    "ray_tpu_shm_swept_bytes_total",
    "Bytes of stale /dev/shm/ray_tpu_* segments (owner process dead — "
    "a SIGKILLed run's leak) removed by the startup sweeper",
)
OBJECT_AGE_SECONDS = Histogram(
    "ray_tpu_object_age_seconds",
    "Lifetime of cluster objects at free time (creation to last-ref)",
    boundaries=[0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0],
)
OOM_KILLS_TOTAL = Counter(
    "ray_tpu_oom_kills_total",
    "Workers killed by the node memory monitor under memory pressure",
    tag_keys=("node_id",),
)


def percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted non-empty
    sequence (shared by state.summarize_tasks and the bench evidence
    writers — one definition, so summaries and committed evidence can
    never disagree)."""
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def latency_dist_ms(vals_ms: Sequence[float]) -> Dict[str, float]:
    """{count, p50_ms, p99_ms, mean_ms} of a non-empty ms sample set."""
    vals = sorted(vals_ms)
    return {
        "count": len(vals),
        "p50_ms": round(percentile(vals, 0.50), 3),
        "p99_ms": round(percentile(vals, 0.99), 3),
        "mean_ms": round(sum(vals) / len(vals), 3),
    }


def registered() -> "List[Metric]":
    """Snapshot of the registry (exporters and dashboard generators)."""
    with _registry_lock:
        return list(_registry)


def prometheus_text() -> str:
    """Full registry in Prometheus exposition format (the /metrics body)."""
    lines: List[str] = []
    for m in registered():
        lines.extend(m.expose())
    return "\n".join(lines) + "\n"


def merge_prometheus(chunks: Sequence[str]) -> str:
    """Merge several exposition bodies into one scrape-able document
    (the head's ``/metrics/cluster`` federation). ``# HELP``/``# TYPE``
    headers are kept once per metric family, and duplicate SERIES
    (same metric name + label set) keep their first-seen sample —
    in-process multi-agent clusters (tests, ``cluster_utils.Cluster``)
    share ONE process registry, so every agent reports the same series
    (possibly re-sampled to a different value between chunk renders —
    identity must be the name+labels, not the whole line, or a gauge
    that moved mid-merge duplicates and Prometheus rejects the body);
    per-node series stay distinct through their ``node_id`` tag."""
    seen_headers: set = set()
    seen_series: set = set()
    out: List[str] = []
    for chunk in chunks:
        for line in (chunk or "").splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                key = tuple(parts[1:3])  # ("HELP"|"TYPE", metric name)
                if key in seen_headers:
                    continue
                seen_headers.add(key)
            else:
                series = line.rsplit(" ", 1)[0]  # name{labels}
                if series in seen_series:
                    continue
                seen_series.add(series)
            out.append(line)
    return "\n".join(out) + "\n"


# -- reading an exposition back (one parser for serve.stats, the bench
# cross-checks AND the head's signal-plane history ring — the same
# definition everywhere, so a windowed query and a client-side
# measurement can never disagree about what the text says). Moved here
# from serve/_observability.py (which re-exports) when the signal plane
# made the parser cluster infrastructure rather than a serve detail.

_SAMPLE_RE = _re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)$")
_LABEL_RE = _re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def parse_prometheus(text: str) -> Dict[str, Dict[tuple, float]]:
    """Exposition text -> {metric_name: {sorted (label, value) tuple:
    sample value}} (comments skipped; NaN-free by construction here)."""
    out: Dict[str, Dict[tuple, float]] = {}
    for line in (text or "").splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels_raw, value = m.groups()
        try:
            val = float(value)
        except ValueError:
            continue
        labels = tuple(sorted(_LABEL_RE.findall(labels_raw or "")))
        out.setdefault(name, {})[labels] = val
    return out


def _labels_get(labels: tuple, key: str) -> Optional[str]:
    for k, v in labels:
        if k == key:
            return v
    return None


def sum_counter(parsed: dict, name: str, group_label: str,
                **match: str) -> Dict[str, float]:
    """Sum a family's samples across node_id (and any other untagged
    label), grouped by one label, filtered by exact label matches."""
    out: Dict[str, float] = {}
    for labels, val in (parsed.get(name) or {}).items():
        if any(_labels_get(labels, k) != v for k, v in match.items()):
            continue
        key = _labels_get(labels, group_label) or ""
        out[key] = out.get(key, 0.0) + val
    return out


def histogram_dist(parsed: dict, name: str, **match: str) -> Optional[dict]:
    """One histogram's cumulative buckets/sum/count, summed across
    node_id, filtered by exact label matches (e.g. deployment=...,
    phase=...). Returns {"buckets": [(le, cum)], "sum": s, "count": n}
    or None when no sample matched."""
    buckets: Dict[float, float] = {}
    total = 0.0
    count = 0.0
    seen = False
    for labels, val in (parsed.get(name + "_bucket") or {}).items():
        if any(_labels_get(labels, k) != v for k, v in match.items()):
            continue
        le_raw = _labels_get(labels, "le")
        le = float("inf") if le_raw == "+Inf" else float(le_raw)
        buckets[le] = buckets.get(le, 0.0) + val
        seen = True
    for labels, val in (parsed.get(name + "_sum") or {}).items():
        if not any(_labels_get(labels, k) != v for k, v in match.items()):
            total += val
    for labels, val in (parsed.get(name + "_count") or {}).items():
        if not any(_labels_get(labels, k) != v for k, v in match.items()):
            count += val
    if not seen or count <= 0:
        return None
    return {"buckets": sorted(buckets.items()), "sum": total,
            "count": count}


def quantile_from_buckets(dist: Optional[dict], q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile: linear interpolation inside
    the bucket containing the q-th sample (the +Inf bucket clamps to the
    last finite bound — same convention as PromQL)."""
    if not dist:
        return None
    buckets = dist["buckets"]
    total = dist["count"]
    rank = q * total
    prev_le, prev_cum = 0.0, 0.0
    last_finite = 0.0
    for le, cum in buckets:
        if le != float("inf"):
            last_finite = le
        if cum >= rank and cum > prev_cum:
            if le == float("inf"):
                return last_finite
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = (0.0 if le == float("inf") else le), cum
    return last_finite


def bucket_width_at(dist: Optional[dict], value: float) -> float:
    """Width of the histogram bucket a value falls in — the resolution
    floor for any client/server latency agreement check."""
    if not dist:
        return float("inf")
    prev = 0.0
    for le, _ in dist["buckets"]:
        if le == float("inf"):
            break
        if value <= le:
            return le - prev
        prev = le
    return float("inf")


def diff_parsed(before: dict, after: dict) -> dict:
    """Per-series ``after - before`` (counters/histogram buckets): lets
    a bench isolate ITS requests from whatever the shared registry
    already accumulated."""
    out: Dict[str, Dict[tuple, float]] = {}
    for name, series in after.items():
        base = before.get(name) or {}
        out[name] = {labels: val - base.get(labels, 0.0)
                     for labels, val in series.items()}
    return out


def file_sd_targets(address: str, labels: Optional[Dict[str, str]] = None,
                    path: str = "/metrics/cluster") -> List[dict]:
    """Prometheus file-SD document pointing one scrape job at the head's
    federated endpoint — one entry covers the whole cluster (write it
    with ``json.dump`` to a file named in a ``file_sd_configs`` block,
    with ``metrics_path: /metrics/cluster``)."""
    return [{
        "targets": [address],
        "labels": {"job": "ray_tpu", "__metrics_path__": path,
                   **(labels or {})},
    }]


PROM_CONTENT_TYPE = "text/plain; version=0.0.4"


def serve_metrics(host: str = "127.0.0.1", port: int = 0,
                  routes: Optional[Dict[str, tuple]] = None):
    """HTTP exposition server. ``routes`` maps a path to
    ``(body_fn, content_type)``; defaults to the process registry at
    ``/metrics``. Returns ``(port, shutdown_fn)``."""
    import http.server

    route_map = dict(routes or {})
    route_map.setdefault("/metrics", (prometheus_text, PROM_CONTENT_TYPE))

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            path = self.path.split("?", 1)[0].rstrip("/")
            entry = route_map.get(path or "/metrics")
            if entry is None:
                self.send_response(404)
                self.end_headers()
                return
            fn, ctype = entry
            try:
                body = fn().encode()
            except Exception as e:  # scrape must see the failure, not hang
                self.send_response(500)
                self.end_headers()
                self.wfile.write(repr(e).encode())
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()

    def shutdown():
        server.shutdown()
        server.server_close()

    return server.server_address[1], shutdown


def start_metrics_server(host: str = "127.0.0.1", port: int = 0) -> int:
    """Serve /metrics for Prometheus scraping; returns the bound port."""
    bound, _shutdown = serve_metrics(host, port)
    return bound
