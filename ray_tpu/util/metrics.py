"""User-defined metrics: Counter / Gauge / Histogram + Prometheus text.

Reference parity: ``python/ray/util/metrics.py`` (the user API) and the
Prometheus exposition of ``_private/prometheus_exporter.py``; the OpenCensus
agent pipeline collapses to an in-process registry with a text endpoint.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: "List[Metric]" = []

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
]


class Metric:
    metric_type = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Sequence[str]] = None):
        if not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple:
        merged = {**self._default_tags, **(tags or {})}
        missing = set(self.tag_keys) - set(merged)
        if missing:
            raise ValueError(f"metric {self.name} missing tags {missing}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> bool:
        """Drop one tagged series (e.g. a dead worker's gauges) so the
        exposition doesn't accumulate stale children forever. Returns
        whether the series existed."""
        key = self._key(tags)
        removed = False
        with self._lock:
            for table in ("_values", "_counts", "_sums", "_totals"):
                d = getattr(self, table, None)
                if d is not None and d.pop(key, None) is not None:
                    removed = True
        return removed

    def _fmt_tags(self, key: Tuple) -> str:
        if not self.tag_keys:
            return ""
        inner = ",".join(
            f'{k}="{v}"' for k, v in zip(self.tag_keys, key)
        )
        return "{" + inner + "}"

    def expose(self) -> List[str]:
        raise NotImplementedError


class Counter(Metric):
    metric_type = "counter"

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} counter"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{self._fmt_tags(key)} {v}")
        return out


class Gauge(Metric):
    metric_type = "gauge"

    def __init__(self, name, description="", tag_keys=None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags=None):
        key = self._key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, tags=None):
        self.inc(-value, tags)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} gauge"]
        with self._lock:
            for key, v in self._values.items():
                out.append(f"{self.name}{self._fmt_tags(key)} {v}")
        return out


class Histogram(Metric):
    metric_type = "histogram"

    def __init__(self, name, description="", boundaries=None, tag_keys=None):
        super().__init__(name, description, tag_keys)
        self.boundaries = list(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            import bisect

            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.description}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                base_tags = list(zip(self.tag_keys, key))
                cumulative = 0
                for bound, c in zip(self.boundaries, counts):
                    cumulative += c
                    tags = base_tags + [("le", str(bound))]
                    inner = ",".join(f'{k}="{v}"' for k, v in tags)
                    out.append(f"{self.name}_bucket{{{inner}}} {cumulative}")
                cumulative += counts[-1]
                inner = ",".join(
                    f'{k}="{v}"' for k, v in base_tags + [("le", "+Inf")]
                )
                out.append(f"{self.name}_bucket{{{inner}}} {cumulative}")
                out.append(
                    f"{self.name}_sum{self._fmt_tags(key)} {self._sums[key]}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_tags(key)} {self._totals[key]}"
                )
        return out


# -- node reporter gauges (reference: dashboard/modules/reporter's
# per-worker cpu/mem stats flowing into the Prometheus exporter). The
# node agent's telemetry loop samples /proc for each worker process and
# sets these; a process that runs no agent just exposes the empty
# families. Tagged per worker so one scrape shows the whole node.
WORKER_CPU_PERCENT = Gauge(
    "ray_tpu_worker_cpu_percent",
    "CPU utilization of a worker process (percent of one core)",
    tag_keys=("node_id", "worker_id", "pid"),
)
WORKER_RSS_BYTES = Gauge(
    "ray_tpu_worker_rss_bytes",
    "Resident set size of a worker process in bytes",
    tag_keys=("node_id", "worker_id", "pid"),
)
WORKER_UPTIME_SECONDS = Gauge(
    "ray_tpu_worker_uptime_seconds",
    "Seconds since the worker process was spawned",
    tag_keys=("node_id", "worker_id", "pid"),
)
NODE_WORKER_COUNT = Gauge(
    "ray_tpu_node_worker_count",
    "Live worker processes on a node",
    tag_keys=("node_id",),
)

# -- node drain lifecycle (head-side; the drain coordinator records one
# increment per initiated drain and the wall time from DRAINING to
# deregistration, so preemption churn is visible per reason).
NODE_DRAINS_TOTAL = Counter(
    "ray_tpu_node_drains_total",
    "Node drains initiated, by reason (preemption, autoscaler_idle, ...)",
    tag_keys=("reason",),
)
NODE_DRAIN_DURATION_SECONDS = Histogram(
    "ray_tpu_node_drain_duration_seconds",
    "Wall time from drain start to node deregistration",
    boundaries=[0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0],
    tag_keys=("reason",),
)
NODE_DRAIN_ACTORS_MIGRATED = Counter(
    "ray_tpu_node_drain_actors_migrated_total",
    "Actors proactively reconstructed off draining nodes",
    tag_keys=("reason",),
)


def registered() -> "List[Metric]":
    """Snapshot of the registry (exporters and dashboard generators)."""
    with _registry_lock:
        return list(_registry)


def prometheus_text() -> str:
    """Full registry in Prometheus exposition format (the /metrics body)."""
    lines: List[str] = []
    for m in registered():
        lines.extend(m.expose())
    return "\n".join(lines) + "\n"


def start_metrics_server(host: str = "127.0.0.1", port: int = 0) -> int:
    """Serve /metrics for Prometheus scraping; returns the bound port."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server.server_address[1]
