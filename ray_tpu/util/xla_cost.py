"""Static cost accounting for compiled step functions (the MFU ground
truth).

Every MFU number this repo committed before round-19 was hand-derived:
``measure.py`` multiplies ``gpt2_flops_per_token`` (the PaLM appendix-B
estimate) by tok/s. That formula silently diverges from what XLA
actually compiled — fused ops, remat, optimizer FLOPs, padding — so the
step anatomy plane computes cost from the compiled HLO instead:
``jitted.lower(*args).compile().cost_analysis()`` gives FLOPs and bytes
accessed for the exact program the device runs, ``memory_analysis()``
the argument/output/temp footprint. From those, arithmetic intensity
and the roofline position against the ``measure.py`` per-device-kind
peak table (plus the HBM-bandwidth table below) decide compute- vs
memory-bound *before* any step is timed; MFU then divides measured
step FLOP/s by the same peak the roofline used.

Off-jax discipline (the ``device_telemetry`` idiom): this module NEVER
imports jax itself — a node agent must not initialize a backend and
steal the chip from its workers. Every entry point degrades to a stub
with ``available=False`` when jax is not already loaded or the cost
query fails, so callers can ship the dict unconditionally.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

# Peak HBM GB/s per chip by device kind substring (roofline ridge
# denominators; same substring-match protocol as measure.PEAK_TFLOPS).
PEAK_HBM_GBPS = {
    "v5 lite": 819.0,
    "v5litepod": 819.0,
    "v5e": 819.0,
    "v4": 1228.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
    "cpu": 50.0,  # nominal DDR, so the roofline still renders off-TPU
}

DEFAULT_HBM_GBPS = 819.0  # unknown accelerator: assume v5e


def jax_loaded() -> bool:
    """Has something in this process already imported jax? (We piggyback
    on their import; we never trigger one.)"""
    return "jax" in sys.modules


def peak_hbm_bytes_per_s(device_kind: str) -> float:
    kind = (device_kind or "").lower()
    for key, gbps in PEAK_HBM_GBPS.items():
        if key in kind:
            return gbps * 1e9
    return DEFAULT_HBM_GBPS * 1e9


def stub(reason: str = "jax not loaded") -> Dict[str, Any]:
    """The off-jax / on-failure shape: same keys a caller branches on,
    ``available=False`` so nothing downstream mistakes it for a cost."""
    return {"available": False, "reason": reason}


def _device_kind() -> str:
    if not jax_loaded():
        return ""
    try:
        import jax

        d = jax.devices()[0]
        return getattr(d, "device_kind", "") or d.platform
    except Exception:
        return ""


def _merge_cost_analysis(cost: Any) -> Dict[str, float]:
    """``compiled.cost_analysis()`` returns a list of per-program dicts
    on jax>=0.4 (one per partition; usually length 1) or a bare dict on
    older versions. Sum the numeric keys we account for."""
    if cost is None:
        return {}
    entries = cost if isinstance(cost, (list, tuple)) else [cost]
    out = {"flops": 0.0, "bytes accessed": 0.0}
    seen = False
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        seen = True
        for key in out:
            try:
                out[key] += float(entry.get(key, 0.0) or 0.0)
            except (TypeError, ValueError):
                pass
    return out if seen else {}


def analyze_compiled(compiled: Any,
                     device_kind: Optional[str] = None) -> Dict[str, Any]:
    """Cost-account an already-compiled executable (the output of
    ``jitted.lower(*args).compile()``)."""
    try:
        merged = _merge_cost_analysis(compiled.cost_analysis())
    except Exception as exc:  # backend without cost_analysis support
        return stub(f"cost_analysis failed: {exc!r}")
    if not merged:
        return stub("cost_analysis returned no per-program entries")
    flops = merged.get("flops", 0.0)
    bytes_accessed = merged.get("bytes accessed", 0.0)
    kind = device_kind if device_kind is not None else _device_kind()
    # Lazy import: scripts.measure owns the peak-FLOPs table (the MFU
    # denominators the committed evidence already uses) and is
    # dependency-free, but util must not import scripts at module load.
    from ray_tpu.scripts.measure import peak_flops_per_chip

    peak_flops = peak_flops_per_chip(kind)
    peak_bw = peak_hbm_bytes_per_s(kind)
    intensity = flops / bytes_accessed if bytes_accessed > 0 else 0.0
    ridge = peak_flops / peak_bw if peak_bw > 0 else 0.0
    out: Dict[str, Any] = {
        "available": True,
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "intensity_flops_per_byte": round(intensity, 3),
        "device_kind": kind,
        "peak_flops": peak_flops,
        "peak_hbm_bytes_per_s": peak_bw,
        "ridge_flops_per_byte": round(ridge, 3),
        "roofline": "compute-bound" if intensity >= ridge
        else "memory-bound",
        "roofline_frac": round(intensity / ridge, 4) if ridge > 0 else 0.0,
    }
    try:
        mem = compiled.memory_analysis()
        out["memory"] = {
            "argument_bytes": int(getattr(
                mem, "argument_size_in_bytes", 0) or 0),
            "output_bytes": int(getattr(
                mem, "output_size_in_bytes", 0) or 0),
            "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0) or 0),
            "generated_code_bytes": int(getattr(
                mem, "generated_code_size_in_bytes", 0) or 0),
        }
    except Exception:
        out["memory"] = {}
    return out


def step_cost(step_fn: Any, *args: Any,
              device_kind: Optional[str] = None,
              **kwargs: Any) -> Dict[str, Any]:
    """Cost-account a jitted step function against example arguments.

    ``step_fn`` must be a ``jax.jit`` product (anything with
    ``.lower``); the lowering traces with the example args' shapes —
    the same specialization the training loop will execute — and the
    compile hits jax's in-process executable cache when the loop
    already compiled this shape."""
    if not jax_loaded():
        return stub()
    if not hasattr(step_fn, "lower"):
        return stub("step_fn has no .lower (not a jax.jit product)")
    try:
        compiled = step_fn.lower(*args, **kwargs).compile()
    except Exception as exc:
        return stub(f"lower/compile failed: {exc!r}")
    return analyze_compiled(compiled, device_kind=device_kind)


def mfu_percent(flops_per_step: float, step_seconds: float,
                device_kind: Optional[str] = None,
                n_devices: int = 1) -> float:
    """Measured model-FLOPs utilization: cost-model FLOPs per step over
    measured step seconds, against the device peak (one chip's peak x
    device count) — the same denominator ``measure.py`` uses, so the
    HLO-derived number is directly comparable to the formula-derived
    one."""
    if step_seconds <= 0 or flops_per_step <= 0:
        return 0.0
    from ray_tpu.scripts.measure import peak_flops_per_chip

    kind = device_kind if device_kind is not None else _device_kind()
    peak = peak_flops_per_chip(kind) * max(1, n_devices)
    if peak <= 0:
        return 0.0
    return flops_per_step / step_seconds / peak * 100.0
