"""Distributed tracing: OTel-shaped spans across task/actor boundaries.

Reference: ``python/ray/util/tracing/tracing_helper.py`` — when tracing is
enabled, every task submission records a client-side span and injects its
context into the task spec; the worker continues the trace around
execution, so one trace follows a request through submit → schedule →
run, across processes. The environment ships only the OpenTelemetry API
(no SDK), so the span model here is self-contained but OTel-shaped:
trace_id/span_id/parent_id hex ids, name, start/end ns, attributes,
status — exportable as JSON lines or a Chrome trace.

Usage:
    from ray_tpu.util import tracing
    tracing.enable()                  # or RAY_TPU_TRACING_ENABLED=1
    with tracing.span("my-step", {"k": "v"}):
        ref = f.remote()              # submit/execute spans attach under it
    spans = tracing.collect()         # this process's finished spans
    tracing.export_chrome_trace("/tmp/trace.json")

Worker-side spans ride the existing worker-events batching to the node
agent and head (``rpc_worker_events`` → LOGS-style aggregation), queryable
via ``head.call("list_spans")``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_enabled = os.environ.get("RAY_TPU_TRACING_ENABLED", "").lower() in (
    "1", "true", "yes", "on")
_finished: List[dict] = []
_MAX_SPANS = 100_000
_dropped = 0  # guarded-by: _lock — spans lost to the _MAX_SPANS cap
_current = threading.local()  # .span = active span dict


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _record(span: dict) -> None:
    global _dropped
    overflow = 0
    with _lock:
        _finished.append(span)
        if len(_finished) > _MAX_SPANS:
            overflow = len(_finished) - _MAX_SPANS
            del _finished[:overflow]
            _dropped += overflow
    if overflow:
        # No silent caps: the truncation that used to vanish here is a
        # counter on the scrape (and rides the worker-events batch to
        # the head, node-attributed, via drain_dropped).
        try:
            from ray_tpu.util import metrics as _metrics

            _metrics.TRACING_DROPPED_SPANS.inc(overflow, tags={
                "node_id": os.environ.get("RAY_TPU_NODE_ID", "local")})
        except Exception:
            pass


def dropped_spans() -> int:
    """Spans this process dropped to the ``_MAX_SPANS`` ring cap."""
    with _lock:
        return _dropped


def drain_dropped() -> int:
    """Pop the drop count accumulated since the last drain (the worker
    event flusher ships this alongside the span batch so the head's
    scrape sees worker-side truncation, not just its own ring's)."""
    global _dropped
    with _lock:
        n = _dropped
        _dropped = 0
    return n


def requeue_dropped(n: int) -> None:
    """Give a drained drop count back (a shipped batch that was itself
    evicted from the resend queue must not silently lose its count)."""
    global _dropped
    if n:
        with _lock:
            _dropped += n


def current_span() -> Optional[dict]:
    return getattr(_current, "span", None)


@contextmanager
def suppressed():
    """Suppress span creation on THIS thread (``span`` yields None).

    Control-plane housekeeping — serve controller health probes,
    autoscaling reconcile passes, routing-table long-polls — submits
    actor calls on its own cadence; without suppression an enabled
    tracer records a ``submit:get_num_ongoing`` span every 250ms
    forever, drowning the request traces the operator actually wants."""
    prev = getattr(_current, "suppress", False)
    _current.suppress = True
    try:
        yield
    finally:
        _current.suppress = prev


def is_suppressed() -> bool:
    return bool(getattr(_current, "suppress", False))


def current_context() -> Optional[dict]:
    """Injectable context of the active span (what task specs carry)."""
    s = current_span()
    if s is None:
        return None
    return {"trace_id": s["trace_id"], "span_id": s["span_id"]}


def _make_span(name: str, attributes: Optional[Dict[str, Any]],
               parent: Optional[dict], cat: Optional[str]) -> dict:
    s = {
        "trace_id": (parent or {}).get("trace_id") or _new_id(16),
        "span_id": _new_id(8),
        "parent_id": (parent or {}).get("span_id"),
        "name": name,
        "start_ns": time.time_ns(),
        "end_ns": None,
        "attributes": dict(attributes or {}),
        "status": "OK",
        "pid": os.getpid(),
    }
    if cat:
        s["cat"] = cat
    return s


def start_span(name: str, attributes: Optional[Dict[str, Any]] = None,
               parent: Optional[dict] = None,
               cat: Optional[str] = None) -> Optional[dict]:
    """Manually-managed span: never touches the thread-local current-
    span stack, so it is safe to hold OPEN across ``await`` points in
    async code (where interleaved coroutines on one thread would
    corrupt a context-manager span's restore order). Pass ``parent={}``
    to force a fresh root. Close with :func:`finish_span`."""
    if not _enabled or is_suppressed():
        return None
    if parent is None:
        parent = current_context()
    return _make_span(name, attributes, parent, cat)


def finish_span(s: Optional[dict], status: str = "OK") -> None:
    """End and record a :func:`start_span` span."""
    if s is None:
        return
    s["end_ns"] = time.time_ns()
    if status != "OK":
        s["status"] = status
    _record(s)


@contextmanager
def span(name: str, attributes: Optional[Dict[str, Any]] = None,
         parent: Optional[dict] = None, cat: Optional[str] = None):
    """Start a span; ``parent`` is an injected context from another
    process (or None to nest under this thread's active span).
    ``cat`` labels the span's Chrome-trace category (default "span");
    the Serve request path uses ``cat="serve"`` so request traces are
    filterable from task spans in one merged timeline."""
    if not _enabled or is_suppressed():
        yield None
        return
    if parent is None:
        parent = current_context()
    s = _make_span(name, attributes, parent, cat)
    prev = getattr(_current, "span", None)
    _current.span = s
    try:
        yield s
    except BaseException as e:
        s["status"] = f"ERROR: {type(e).__name__}"
        raise
    finally:
        s["end_ns"] = time.time_ns()
        _current.span = prev
        _record(s)


# -- W3C Trace Context (the HTTP proxy's wire format) ----------------------
#
# ``traceparent: 00-<32 hex trace id>-<16 hex parent span id>-<2 hex flags>``
# — the standard header external clients/gateways already emit, so an
# ingress request joins its caller's distributed trace.


def parse_traceparent(header: Optional[str]) -> Optional[dict]:
    """W3C ``traceparent`` header -> injectable span context (or None on
    anything malformed — a bad header must never fail the request)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4 or parts[0] == "ff":
        return None
    trace_id, span_id = parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
    except ValueError:
        return None
    return {"trace_id": trace_id.lower(), "span_id": span_id.lower()}


def format_traceparent(ctx: Optional[dict]) -> Optional[str]:
    """Span context -> W3C ``traceparent`` header value."""
    if not ctx or not ctx.get("trace_id") or not ctx.get("span_id"):
        return None
    return f"00-{ctx['trace_id']}-{ctx['span_id']}-01"


def collect(clear: bool = False) -> List[dict]:
    with _lock:
        out = list(_finished)
        if clear:
            del _finished[:]
    return out


def drain() -> List[dict]:
    """Pop this process's finished spans (used by the worker's event
    flusher to ship spans to the node agent in batches)."""
    return collect(clear=True)


def export_jsonl(path: str) -> int:
    spans = collect()
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return len(spans)


def export_otel(spans: Optional[List[dict]] = None,
                tracer_name: str = "ray_tpu") -> int:
    """Re-emit finished spans through the OpenTelemetry API (reference
    tracing_helper.py emits OTel spans directly). The environment ships
    only the OTel API — with no provider configured this is a no-op by
    OTel's own design; when the application installs a provider (OTLP,
    Jaeger, ...), the same call exports there.

    Span-id note: an SDK always mints fresh span ids (the API offers no
    way to force ours), so the TREE is preserved by re-emitting in
    topological order and parenting each child under the freshly created
    parent span; only spans whose parent is outside the batch fall back
    to a remote NonRecordingSpan context with the original ids."""
    import opentelemetry.trace as ot
    from opentelemetry.trace import (
        NonRecordingSpan,
        SpanContext,
        TraceFlags,
        set_span_in_context,
    )

    spans = spans if spans is not None else collect()
    tracer = ot.get_tracer(tracer_name)
    by_id = {s["span_id"]: s for s in spans}
    created: Dict[str, Any] = {}  # our span_id -> emitted otel span
    n = 0

    def emit(s: dict):
        nonlocal n
        sid = s["span_id"]
        if sid in created:
            return created[sid]
        parent_id = s.get("parent_id")
        ctx = None
        if parent_id:
            if parent_id in by_id:
                # In-batch parent: emit it first, nest under ITS fresh id.
                ctx = set_span_in_context(emit(by_id[parent_id]))
            else:
                ctx = set_span_in_context(NonRecordingSpan(SpanContext(
                    trace_id=int(s["trace_id"], 16),
                    span_id=int(parent_id, 16),
                    is_remote=True,
                    trace_flags=TraceFlags(TraceFlags.SAMPLED),
                )))
        otel_span = tracer.start_span(
            s["name"], context=ctx, start_time=s.get("start_ns"),
            attributes={k: str(v) for k, v in
                        (s.get("attributes") or {}).items()},
        )
        if s.get("status") and s["status"] != "OK":
            from opentelemetry.trace import Status, StatusCode

            otel_span.set_status(Status(StatusCode.ERROR, s["status"]))
        otel_span.end(end_time=s.get("end_ns"))
        created[sid] = otel_span
        n += 1
        return otel_span

    for s in spans:
        emit(s)
    return n


def chrome_events(spans: List[dict]) -> List[dict]:
    """Chrome trace 'X' events, mergeable with ``state.timeline()``'s
    task/phase slices into one trace (distinct ``cat`` so a merged view
    can filter spans vs task slices)."""
    return [
        {
            "name": s["name"],
            "cat": s.get("cat") or "span",
            "ph": "X",
            "ts": s["start_ns"] / 1e3,
            "dur": ((s["end_ns"] or s["start_ns"]) - s["start_ns"]) / 1e3,
            "pid": s.get("pid", 0),
            "tid": s["trace_id"][:8],
            "args": {**s["attributes"], "status": s["status"],
                     "span_id": s["span_id"],
                     "parent_id": s.get("parent_id")},
        }
        for s in spans
    ]


def export_chrome_trace(path: str, spans: Optional[List[dict]] = None) -> int:
    spans = collect() if spans is None else spans
    with open(path, "w") as f:
        json.dump(chrome_events(spans), f)
    return len(spans)
