"""GPT-2 in pure JAX, sharding-annotated, scan-over-layers, remat-able.

This is the flagship training workload (BASELINE.json: GPT-2 Train benchmark,
target >=45% MFU on a v4 slice). Design choices for TPU:

* Parameters are a plain pytree of arrays plus a parallel pytree of *logical
  axis names* (``gpt2_param_axes``); physical shardings come from
  ``ray_tpu.parallel.sharding`` rules — Megatron TP on mlp/heads/vocab dims,
  ZeRO-3 (fsdp) on the embed dim, pp over the stacked layer dim.
* Transformer blocks are **stacked** ([n_layer, ...] leaves) and iterated
  with `lax.scan` => O(1) compile time in depth, and the block body is
  `jax.checkpoint`-ed so activations are rematerialized in backward
  (HBM-for-FLOPs trade, SURVEY.md §"HBM bandwidth").
* Compute in bf16 (MXU-native), params + optimizer state in fp32, softmax
  and loss in fp32.

Reference parity note: the reference trains GPT-2 through torch DDP wrapped
in Ray Train (``release/air_tests/air_benchmarks``); here the model is owned
by the framework and compiled as one pjit program.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import causal_attention
from ray_tpu.parallel.sharding import logical_sharding, with_logical_constraint

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # 50257 padded to a multiple of 128 for the MXU
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    seq_len: int = 1024
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32
    # Rematerialization of the block body in backward (HBM-for-FLOPs):
    #   True   — full remat (lowest memory, recomputes the whole forward);
    #   "dots" — selective: save matmul outputs, recompute elementwise only
    #            (jax.checkpoint_policies.dots_with_no_batch_dims_saveable);
    #   False  — save everything (needs flash attention to fit at seq 1024).
    remat: Any = True
    # Iterate the stacked blocks with lax.scan (O(1) compile time in depth)
    # or a Python loop (unrolled: XLA schedules across layer boundaries —
    # measured ~25% faster fwd+bwd on v5e at 12 layers, at higher compile
    # cost; use for the single-slice training hot path).
    scan_layers: bool = True
    use_flash: bool | None = None  # None = auto by seq_len/backend
    # Attention parallelism: "auto" (GSPMD-partitioned dense/flash),
    # "ring" (sp-axis ring attention, ppermute KV), or "ulysses"
    # (sp-axis all_to_all head scatter). ring/ulysses need ``mesh``.
    attention_impl: str = "auto"
    # LM-head matmul output dtype (MaxText-style). None = fp32 logits
    # (stable default). jnp.bfloat16 doubles the head matmul rate on the
    # MXU (measured 59 -> ~120 TF/s for fp32- vs bf16-out on v5e) and
    # halves logits HBM traffic; CE reductions still accumulate in fp32.
    logits_dtype: Any = None
    # Fused Pallas norm/residual/GELU kernels (ops/fused_norm.py): the
    # LayerNorm forward saves only fp32 mean/rstd, and ONE backward
    # kernel per row-block fuses dx/dscale/dbias with the residual-add
    # gradient, so the fp32 LN recompute chain XLA materializes
    # (PROFILE.md sink #3, ~15ms/step) never reaches HBM. The MLP GELU
    # rides a fused tanh backward epilogue. Shapes the TPU lane layout
    # can't tile (D % 128 != 0) fall back to the plain-XLA chain.
    fused_norm: bool = False
    # Cross-entropy over vocab chunks (>1 enables): the loss runs an
    # online-logsumexp lax.scan over [V/n, D] slices of the tied head so
    # the full [B, T, V] logits tensor is NEVER materialized — fwd or
    # bwd (per-chunk remat recomputes chunk logits in backward). Cuts
    # the loss-path HBM footprint by n_chunks x, unblocking larger
    # batches (PROFILE.md: fp32 [16,1024,50304] logits forced spills at
    # batch >= 24). Must divide vocab_size.
    ce_vocab_chunks: int = 1
    mesh: Any = dataclasses.field(default=None, compare=False)

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_head

    @property
    def n_params(self) -> int:
        """Parameter count (tied embeddings)."""
        d, l, v, s = self.d_model, self.n_layer, self.vocab_size, self.seq_len
        per_layer = 12 * d * d + 13 * d  # qkv+proj+mlp weights & biases + 2 LN
        return v * d + s * d + l * per_layer + 2 * d

    @classmethod
    def small(cls) -> "GPT2Config":
        return cls()  # 124M

    @classmethod
    def medium(cls) -> "GPT2Config":
        return cls(n_layer=24, n_head=16, d_model=1024)

    @classmethod
    def tiny(cls) -> "GPT2Config":
        """CPU-test sized."""
        return cls(vocab_size=256, n_layer=2, n_head=4, d_model=64, seq_len=64)


def gpt2_param_axes(cfg: GPT2Config) -> Params:
    """Logical axis names for every param leaf (same tree structure)."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            # leading dim is the stacked layer dim
            "ln1_scale": ("layers", None),
            "ln1_bias": ("layers", None),
            "attn_qkv_w": ("layers", "embed", "qkv"),
            "attn_qkv_b": ("layers", "qkv"),
            "attn_out_w": ("layers", "qkv", "embed"),
            "attn_out_b": ("layers", None),
            "ln2_scale": ("layers", None),
            "ln2_bias": ("layers", None),
            "mlp_in_w": ("layers", "embed", "mlp"),
            "mlp_in_b": ("layers", "mlp"),
            "mlp_out_w": ("layers", "mlp", "embed"),
            "mlp_out_b": ("layers", None),
        },
        "lnf_scale": (None,),
        "lnf_bias": (None,),
    }


def gpt2_shardings(cfg: GPT2Config, mesh, rules=None) -> Params:
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        gpt2_param_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def gpt2_init(rng: jax.Array, cfg: GPT2Config) -> Params:
    """GPT-2 init: normal(0.02), residual projections scaled by 1/sqrt(2L)."""
    d, l, v, s = cfg.d_model, cfg.n_layer, cfg.vocab_size, cfg.seq_len
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 8))
    std = 0.02
    resid_std = std / math.sqrt(2 * l)

    def norm(key, shape, stddev):
        return (jax.random.normal(key, shape, jnp.float32) * stddev).astype(pd)

    return {
        "wte": norm(next(k), (v, d), std),
        "wpe": norm(next(k), (s, d), std),
        "blocks": {
            "ln1_scale": jnp.ones((l, d), pd),
            "ln1_bias": jnp.zeros((l, d), pd),
            "attn_qkv_w": norm(next(k), (l, d, 3 * d), std),
            "attn_qkv_b": jnp.zeros((l, 3 * d), pd),
            "attn_out_w": norm(next(k), (l, d, d), resid_std),
            "attn_out_b": jnp.zeros((l, d), pd),
            "ln2_scale": jnp.ones((l, d), pd),
            "ln2_bias": jnp.zeros((l, d), pd),
            "mlp_in_w": norm(next(k), (l, d, 4 * d), std),
            "mlp_in_b": jnp.zeros((l, 4 * d), pd),
            "mlp_out_w": norm(next(k), (l, 4 * d, d), resid_std),
            "mlp_out_b": jnp.zeros((l, d), pd),
        },
        "lnf_scale": jnp.ones((d,), pd),
        "lnf_bias": jnp.zeros((d,), pd),
    }


def _layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + 1e-5)
    return (y * scale + bias).astype(x.dtype)


def _norm_residual(x: jax.Array, scale: jax.Array, bias: jax.Array,
                   cfg: GPT2Config) -> tuple[jax.Array, jax.Array]:
    """(LN(x), residual-skip x). With ``cfg.fused_norm`` the skip rides
    through the fused op so the residual-add gradient lands inside the
    one Pallas backward kernel."""
    if cfg.fused_norm:
        from ray_tpu.ops.fused_norm import fused_layer_norm_residual

        return fused_layer_norm_residual(x, scale, bias)
    return _layer_norm(x, scale, bias), x


def _block(x: jax.Array, p: Params, cfg: GPT2Config) -> jax.Array:
    """One transformer block. x: [B, T, D] in cfg.dtype."""
    b, t, d = x.shape
    h, hd = cfg.n_head, cfg.head_dim
    dt = cfg.dtype

    y, x_skip = _norm_residual(x, p["ln1_scale"], p["ln1_bias"], cfg)
    qkv = y @ p["attn_qkv_w"].astype(dt) + p["attn_qkv_b"].astype(dt)
    q, k_, v_ = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, t, h, hd)
    k_ = k_.reshape(b, t, h, hd)
    v_ = v_.reshape(b, t, h, hd)
    if cfg.attention_impl == "ring" and cfg.mesh is not None:
        from ray_tpu.ops.ring_attention import ring_causal_attention

        attn = ring_causal_attention(q, k_, v_, cfg.mesh, axis="sp")
    elif cfg.attention_impl == "ulysses" and cfg.mesh is not None:
        from ray_tpu.ops.ulysses import ulysses_attention

        attn = ulysses_attention(q, k_, v_, cfg.mesh, axis="sp")
    else:
        attn = causal_attention(q, k_, v_, use_flash=cfg.use_flash)
    attn = attn.reshape(b, t, d)
    x = x_skip + attn @ p["attn_out_w"].astype(dt) + p["attn_out_b"].astype(dt)
    x = with_logical_constraint(x, ("batch", "seq", None))

    y, x_skip = _norm_residual(x, p["ln2_scale"], p["ln2_bias"], cfg)
    y = y @ p["mlp_in_w"].astype(dt) + p["mlp_in_b"].astype(dt)
    y = with_logical_constraint(y, ("batch", "seq", "mlp"))
    if cfg.fused_norm:
        from ray_tpu.ops.fused_norm import fused_gelu

        y = fused_gelu(y)
    else:
        y = jax.nn.gelu(y, approximate=True)
    x = x_skip + y @ p["mlp_out_w"].astype(dt) + p["mlp_out_b"].astype(dt)
    x = with_logical_constraint(x, ("batch", "seq", None))
    return x


def gpt2_hidden(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> final-layernormed hidden states [B, T, D]."""
    _, t = tokens.shape
    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:t]
    x = with_logical_constraint(x, ("batch", "seq", None))

    block_fn = lambda carry, p: (_block(carry, p, cfg), None)
    if cfg.remat == "dots":
        block_fn = jax.checkpoint(
            block_fn, prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif cfg.remat:
        block_fn = jax.checkpoint(block_fn, prevent_cse=False)
    if cfg.scan_layers:
        x, _ = jax.lax.scan(block_fn, x, params["blocks"])
    else:
        for i in range(cfg.n_layer):
            x, _ = block_fn(
                x, jax.tree.map(lambda a: a[i], params["blocks"])
            )

    if cfg.fused_norm:
        from ray_tpu.ops.fused_norm import fused_layer_norm

        return fused_layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    return _layer_norm(x, params["lnf_scale"], params["lnf_bias"])


def _head_dtype(cfg: GPT2Config):
    return cfg.logits_dtype if cfg.logits_dtype is not None else jnp.float32


def gpt2_forward(params: Params, tokens: jax.Array, cfg: GPT2Config) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, V] (fp32 unless cfg.logits_dtype)."""
    x = gpt2_hidden(params, tokens, cfg)
    # Tied LM head; fp32 logits by default for a stable loss.
    logits = jnp.einsum(
        "btd,vd->btv", x, params["wte"].astype(cfg.dtype),
        preferred_element_type=_head_dtype(cfg),
    )
    return logits


def _chunked_ce(x: jax.Array, wte: jax.Array, targets: jax.Array,
                cfg: GPT2Config) -> jax.Array:
    """Online-logsumexp cross-entropy over vocab chunks.

    The head matmul + reductions run chunk-at-a-time under ``lax.scan``
    with per-chunk remat, so peak logits memory is [B, T, V/n] in both
    forward AND backward (the reference analog materializes the full
    fp32 [B, T, V] twice; cf. flash attention's online-softmax trick,
    applied to the vocab axis)."""
    n = cfg.ce_vocab_chunks
    v, d = wte.shape
    if v % n:
        raise ValueError(f"ce_vocab_chunks={n} must divide vocab_size={v}")
    vc = v // n
    w_chunks = wte.reshape(n, vc, d).astype(cfg.dtype)
    bases = jnp.arange(n, dtype=targets.dtype) * vc

    def body(carry, inp):
        m, s, picked = carry
        wc, base = inp
        logits = jnp.einsum(
            "btd,vd->btv", x, wc, preferred_element_type=_head_dtype(cfg)
        ).astype(jnp.float32)
        cmax = logits.max(axis=-1)
        new_m = jnp.maximum(m, cmax)
        s = s * jnp.exp(m - new_m) + jnp.exp(
            logits - new_m[..., None]).sum(axis=-1)
        idx = jnp.clip(targets - base, 0, vc - 1)
        p = jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0]
        picked = jnp.where((targets >= base) & (targets < base + vc),
                           p, picked)
        return (new_m, s, picked), None

    bt = targets.shape
    init = (
        jnp.full(bt, -jnp.inf, jnp.float32),   # running max
        jnp.zeros(bt, jnp.float32),            # running sum(exp(l - max))
        jnp.zeros(bt, jnp.float32),            # picked target logit
    )
    (m, s, picked), _ = jax.lax.scan(
        jax.checkpoint(body, prevent_cse=False), init, (w_chunks, bases))
    return jnp.mean(m + jnp.log(s) - picked)


def gpt2_loss(params: Params, batch: dict[str, jax.Array], cfg: GPT2Config) -> jax.Array:
    """Next-token cross-entropy. batch: {'tokens': [B, T+1] or [B, T] int32}.

    If only [B, T] is given, inputs are tokens[:, :-1], targets tokens[:, 1:].
    """
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    if cfg.ce_vocab_chunks > 1:
        x = gpt2_hidden(params, inputs, cfg)
        return _chunked_ce(x, params["wte"], targets, cfg)
    logits = gpt2_forward(params, inputs, cfg)
    # CE via logsumexp - picked logit: one reduction pass over [B,T,V]
    # instead of materializing log_softmax (measured ~2x faster fwd on
    # v5e at V=50k; the softmax only appears in the backward). The
    # reductions run in fp32 even when cfg.logits_dtype is bf16.
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)


# -- autoregressive decoding (serving path) --------------------------------
#
# The serving engine (``ray_tpu/serve/llm_engine.py``) owns ONE jitted
# decode step over a fixed ``[max_batch, ...]`` state and admits requests
# between steps, so these functions are shape-stable by construction:
#
# * ``gpt2_init_cache``   — slot-indexed ring KV-cache in device memory,
#   ``[n_layer, slots, cache_len, n_head, head_dim]`` in the activation
#   dtype (bf16 by default — no fp32 cache copy ever materializes);
# * ``gpt2_prefill``      — the second jitted shape: a fixed
#   ``[rows, prompt_len]`` chunked-prefill lane writing each prompt's
#   K/V into its slot's cache rows and sampling the FIRST token from the
#   last real position's logits;
# * ``gpt2_decode_step``  — one token for every slot: write this token's
#   K/V at the slot's ring cursor (``lax.dynamic_update_slice`` vmapped
#   over slots), attend over the valid cache window, next-token logits.
#
# Ring semantics: the write cursor is ``pos % cache_len`` and the
# attention mask covers ``min(pos + 1, cache_len)`` entries — a
# generation longer than the cache degrades to sliding-window attention
# instead of erroring. Positions (wpe rows) use the absolute position,
# clamped to ``seq_len``.


def gpt2_init_cache(cfg: GPT2Config, slots: int, cache_len: int) -> Params:  # decode-path
    """Ring KV-cache for ``slots`` concurrent sequences (bf16 by default:
    the cache rides ``cfg.dtype``, never fp32)."""
    shape = (cfg.n_layer, slots, cache_len, cfg.n_head, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


# jax-hot-path: traced into the engine's single compiled decode step
def gpt2_decode_step(params: Params, cache: Params, tokens: jax.Array,
                     pos: jax.Array, cfg: GPT2Config
                     ) -> tuple[jax.Array, Params]:
    """One decode iteration for every slot.

    tokens [S] int32 (the slot's current token), pos [S] int32 (its
    absolute position). Writes each token's K/V at the slot's ring
    cursor, attends over the valid window, and returns
    (logits [S, V] fp32, new cache). Free slots simply compute garbage
    into their own cache rows — the fixed shape is the point."""
    s = tokens.shape[0]
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    cache_len = cache["k"].shape[2]
    dt = cfg.dtype
    cursor = jnp.mod(pos, cache_len)
    valid = jnp.minimum(pos + 1, cache_len)
    wpe_pos = jnp.clip(pos, 0, cfg.seq_len - 1)
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[wpe_pos]

    from ray_tpu.ops.attention import (cache_write_token,
                                       cached_decode_attention)

    def block(x, layer):
        p, k_cache, v_cache = layer
        y = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
        qkv = y @ p["attn_qkv_w"].astype(dt) + p["attn_qkv_b"].astype(dt)
        q, k_new, v_new = jnp.split(qkv, 3, axis=-1)
        k_cache = cache_write_token(
            k_cache, k_new.reshape(s, 1, h, hd), cursor)
        v_cache = cache_write_token(
            v_cache, v_new.reshape(s, 1, h, hd), cursor)
        attn = cached_decode_attention(
            q.reshape(s, h, hd), k_cache, v_cache, valid, dt)
        x = x + attn.reshape(s, d) @ p["attn_out_w"].astype(dt) \
            + p["attn_out_b"].astype(dt)
        y = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
        y = y @ p["mlp_in_w"].astype(dt) + p["mlp_in_b"].astype(dt)
        y = jax.nn.gelu(y, approximate=True)
        x = x + y @ p["mlp_out_w"].astype(dt) + p["mlp_out_b"].astype(dt)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    logits = jnp.einsum(
        "sd,vd->sv", x, params["wte"].astype(dt),
        preferred_element_type=jnp.float32)
    return logits, {"k": k_all, "v": v_all}


# jax-hot-path: traced into the engine's single compiled prefill lane
def gpt2_prefill(params: Params, cache: Params, tokens: jax.Array,
                 slots: jax.Array, lengths: jax.Array, cfg: GPT2Config
                 ) -> tuple[jax.Array, Params]:
    """Chunked-prefill lane: the engine's SECOND (and only other) jitted
    shape.

    tokens [R, P] int32 zero-padded prompts, slots [R] int32 (each row's
    target cache slot; point unused rows at a scratch slot), lengths [R]
    int32. Runs the full causal forward over the padded window, writes
    rows ``[0, P)`` of each target slot's K/V cache, and returns
    (logits [R, V] fp32 at each prompt's last real token, new cache).
    Rows past a prompt's length hold pad garbage; the decode mask never
    reads them — the slot's own later writes overwrite them in order."""
    r, p_len = tokens.shape
    d, h, hd = cfg.d_model, cfg.n_head, cfg.head_dim
    dt = cfg.dtype
    x = params["wte"].astype(dt)[tokens] + params["wpe"].astype(dt)[:p_len]
    from ray_tpu.ops.attention import cache_write_prompt

    def block(x, layer):
        p, k_cache, v_cache = layer
        y = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
        qkv = y @ p["attn_qkv_w"].astype(dt) + p["attn_qkv_b"].astype(dt)
        q, k_, v_ = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(r, p_len, h, hd)
        k_ = k_.reshape(r, p_len, h, hd)
        v_ = v_.reshape(r, p_len, h, hd)
        attn = causal_attention(q, k_, v_, use_flash=False)
        k_cache = cache_write_prompt(k_cache, k_, slots)
        v_cache = cache_write_prompt(v_cache, v_, slots)
        x = x + attn.reshape(r, p_len, d) @ p["attn_out_w"].astype(dt) \
            + p["attn_out_b"].astype(dt)
        y = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
        y = y @ p["mlp_in_w"].astype(dt) + p["mlp_in_b"].astype(dt)
        y = jax.nn.gelu(y, approximate=True)
        x = x + y @ p["mlp_out_w"].astype(dt) + p["mlp_out_b"].astype(dt)
        return x, (k_cache, v_cache)

    x, (k_all, v_all) = jax.lax.scan(
        block, x, (params["blocks"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["lnf_scale"], params["lnf_bias"])
    last = x[jnp.arange(r), jnp.clip(lengths - 1, 0, p_len - 1)]  # [R, D]
    logits = jnp.einsum(
        "rd,vd->rv", last, params["wte"].astype(dt),
        preferred_element_type=jnp.float32)
    return logits, {"k": k_all, "v": v_all}


def gpt2_flops_per_token(cfg: GPT2Config, seq_len: int | None = None) -> float:
    """Training FLOPs/token: 6*N for matmuls + attention score/value FLOPs.

    Standard estimate (PaLM appendix B): 6*n_params + 12*L*D*T (causal)."""
    t = seq_len or cfg.seq_len
    return 6 * cfg.n_params + 12 * cfg.n_layer * cfg.d_model * t // 2
