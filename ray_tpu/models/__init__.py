"""Model zoo: pure-JAX pytree models designed for pjit sharding.

Flagship: GPT-2 (the BASELINE.json north-star workload). Models are plain
functions over parameter pytrees — no framework Module state — so the same
code runs under any mesh and any rules table.
"""

from ray_tpu.models.gpt2 import GPT2Config, gpt2_forward, gpt2_init, gpt2_loss
from ray_tpu.models.llama import (
    LlamaConfig,
    llama_forward,
    llama_init,
    llama_loss,
)
from ray_tpu.models.moe import (
    MoEConfig,
    moe_forward,
    moe_init,
    moe_loss,
)

__all__ = [
    "GPT2Config",
    "LlamaConfig",
    "MoEConfig",
    "gpt2_forward",
    "gpt2_init",
    "gpt2_loss",
    "llama_forward",
    "llama_init",
    "llama_loss",
    "moe_forward",
    "moe_init",
    "moe_loss",
]
