"""Mixtral-style MoE decoder: Llama attention + mixture-of-experts FFN.

Third model family (after ``models/gpt2.py`` and ``models/llama.py``):
demonstrates expert parallelism end to end — each layer's SwiGLU MLP is
replaced by a top-k routed expert mixture (``ops/moe.py``), with expert
weights sharded over the mesh's ``ep`` axis and tokens exchanged by
``all_to_all`` when expert parallelism is on. The training loss carries
the router's load-balancing auxiliary term (switch-transformer style).

Reference parity note: the reference has no model zoo (torch owns its
compute path); on TPU the framework owns the compute path, and MoE is
the §2.4 EP strategy exercised in a real model.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, _rms_norm, _rope
from ray_tpu.ops.attention import causal_attention
from ray_tpu.ops.moe import init_moe_params, moe_ffn, moe_ffn_ep, moe_param_axes
from ray_tpu.parallel.sharding import logical_sharding, with_logical_constraint

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    """Llama geometry + expert mixture. ``expert_parallel`` switches the
    FFN to the all_to_all path (requires a mesh with an ``ep`` axis)."""

    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.01
    expert_parallel: bool = False

    @classmethod
    def tiny(cls) -> "MoEConfig":
        return cls(vocab_size=256, n_layer=2, n_head=4, n_kv_head=2,
                   d_model=64, seq_len=64, n_experts=4, top_k=2)

    @property
    def n_params(self) -> int:
        d, hd = self.d_model, self.head_dim
        attn = d * (self.n_head * hd) + 2 * d * (self.n_kv_head * hd) \
            + (self.n_head * hd) * d
        moe = d * self.n_experts + 2 * self.n_experts * d * self.d_ff
        per_layer = attn + moe + 2 * d
        return (self.vocab_size * d + self.n_layer * per_layer
                + d + d * self.vocab_size)

    @property
    def n_active_params(self) -> int:
        """Params touched per token (top_k of n_experts) — the MoE
        efficiency headline."""
        d = self.d_model
        dense = self.n_params - self.n_layer * 2 * self.n_experts * d * self.d_ff
        return dense + self.n_layer * 2 * self.top_k * d * self.d_ff


def moe_param_axes_tree(cfg: MoEConfig) -> Params:
    stack = lambda axes: ("layers", *axes)
    m = {k: stack(v) for k, v in moe_param_axes().items()}
    return {
        "embed": ("vocab", "embed"),
        "blocks": {
            "attn_norm": ("layers", None),
            "wq": ("layers", "embed", "qkv"),
            "wk": ("layers", "embed", "qkv"),
            "wv": ("layers", "embed", "qkv"),
            "wo": ("layers", "qkv", "embed"),
            "mlp_norm": ("layers", None),
            "moe": m,
        },
        "final_norm": (None,),
        "lm_head": ("embed", "vocab"),
    }


def moe_shardings(cfg: MoEConfig, mesh, rules=None) -> Params:
    return jax.tree.map(
        lambda axes: logical_sharding(mesh, axes, rules),
        moe_param_axes_tree(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def moe_init(rng: jax.Array, cfg: MoEConfig) -> Params:
    d, l, v = cfg.d_model, cfg.n_layer, cfg.vocab_size
    hd, nh, nkv = cfg.head_dim, cfg.n_head, cfg.n_kv_head
    pd = cfg.param_dtype
    k = iter(jax.random.split(rng, 8 + l))

    def norm(key, shape, stddev=0.02):
        return (jax.random.normal(key, shape, jnp.float32) * stddev).astype(pd)

    resid = 0.02 / (2 * l) ** 0.5
    per_layer = [
        init_moe_params(next(k), d, cfg.d_ff, cfg.n_experts, dtype=pd)
        for _ in range(l)
    ]
    moe_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *per_layer)
    return {
        "embed": norm(next(k), (v, d)),
        "blocks": {
            "attn_norm": jnp.ones((l, d), pd),
            "wq": norm(next(k), (l, d, nh * hd)),
            "wk": norm(next(k), (l, d, nkv * hd)),
            "wv": norm(next(k), (l, d, nkv * hd)),
            "wo": norm(next(k), (l, nh * hd, d), resid),
            "mlp_norm": jnp.ones((l, d), pd),
            "moe": moe_stacked,
        },
        "final_norm": jnp.ones((d,), pd),
        "lm_head": norm(next(k), (d, v)),
    }


def _block(x: jax.Array, p: Params, cfg: MoEConfig) -> tuple[jax.Array, jax.Array]:
    b, t, d = x.shape
    nh, nkv, hd = cfg.n_head, cfg.n_kv_head, cfg.head_dim
    dt = cfg.dtype

    y = _rms_norm(x, p["attn_norm"])
    q = (y @ p["wq"].astype(dt)).reshape(b, t, nh, hd)
    k = (y @ p["wk"].astype(dt)).reshape(b, t, nkv, hd)
    v = (y @ p["wv"].astype(dt)).reshape(b, t, nkv, hd)
    q, k = _rope(q, cfg.rope_theta), _rope(k, cfg.rope_theta)
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    attn = causal_attention(q, k, v, use_flash=cfg.use_flash)
    x = x + attn.reshape(b, t, nh * hd) @ p["wo"].astype(dt)
    x = with_logical_constraint(x, ("batch", "seq", None))

    y = _rms_norm(x, p["mlp_norm"])
    if cfg.expert_parallel and cfg.mesh is not None:
        ff, aux = moe_ffn_ep(
            p["moe"], y, cfg.mesh, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor,
            activation=jax.nn.silu,
        )
    else:
        ff, aux = moe_ffn(
            p["moe"], y, top_k=cfg.top_k,
            capacity_factor=cfg.capacity_factor,
            activation=jax.nn.silu,
        )
    x = x + ff
    x = with_logical_constraint(x, ("batch", "seq", None))
    return x, aux


def moe_forward(params: Params, tokens: jax.Array,
                cfg: MoEConfig) -> tuple[jax.Array, jax.Array]:
    """tokens [B, T] -> (logits [B, T, V] fp32, aux_loss scalar)."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[tokens]
    x = with_logical_constraint(x, ("batch", "seq", None))

    def block_fn(carry, p):
        x, aux_sum = carry
        x, aux = _block(x, p, cfg)
        return (x, aux_sum + aux), None

    if cfg.remat == "dots":
        block_fn = jax.checkpoint(
            block_fn, prevent_cse=False,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif cfg.remat:
        block_fn = jax.checkpoint(block_fn, prevent_cse=False)
    if cfg.scan_layers:
        (x, aux), _ = jax.lax.scan(
            block_fn, (x, jnp.zeros((), jnp.float32)), params["blocks"])
    else:
        aux = jnp.zeros((), jnp.float32)
        for i in range(cfg.n_layer):
            (x, aux), _ = block_fn(
                (x, aux), jax.tree.map(lambda a: a[i], params["blocks"]))

    x = _rms_norm(x, params["final_norm"])
    logits = jnp.einsum(
        "btd,dv->btv", x, params["lm_head"].astype(dt),
        preferred_element_type=jnp.float32,
    )
    return logits, aux / cfg.n_layer


def moe_loss(params: Params, batch: dict[str, jax.Array],
             cfg: MoEConfig) -> jax.Array:
    """Cross entropy + router load-balancing auxiliary loss."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = moe_forward(params, inputs, cfg)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked) + cfg.aux_loss_coef * aux
