"""ResNet in pure JAX — the vision training workload.

Reference parity: the reference's ResNet-50 MLPerf-style benchmark
(``release/air_tests/air_benchmarks/mlperf-train/resnet50_ray_air.py``)
trains torch ResNet-50 under Ray Train; here the model is owned by the
framework and compiled as one pjit program.

TPU design notes: convs map onto the MXU via ``lax.conv_general_dilated``
in NHWC (TPU-native layout); normalization is GroupNorm — stateless, so
the train step stays a pure function of (params, batch) with no
running-stat side channel, and it parallelizes over any mesh without
cross-replica batch statistics.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

Params = Any


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    groups: int = 32
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @classmethod
    def resnet18(cls, **kw):
        return cls(stage_sizes=(2, 2, 2, 2), **kw)

    @classmethod
    def resnet50(cls, **kw):
        return cls(stage_sizes=(3, 4, 6, 3), **kw)

    @classmethod
    def tiny(cls):
        """CPU-test sized."""
        return cls(stage_sizes=(1, 1), num_classes=10, width=8, groups=4)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = (2.0 / fan_in) ** 0.5
    return (jax.random.normal(key, (kh, kw, cin, cout)) * std).astype(dtype)


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _group_norm(x, scale, bias, groups):
    b, h, w, c = x.shape
    g = min(groups, c)
    x32 = x.astype(jnp.float32).reshape(b, h, w, g, c // g)
    mu = x32.mean(axis=(1, 2, 4), keepdims=True)
    var = x32.var(axis=(1, 2, 4), keepdims=True)
    x32 = (x32 - mu) * jax.lax.rsqrt(var + 1e-5)
    x32 = x32.reshape(b, h, w, c)
    return (x32 * scale + bias).astype(x.dtype)


def resnet_init(rng: jax.Array, cfg: ResNetConfig) -> Params:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(rng, 1024))
    width = cfg.width

    def norm_params(c):
        return {"scale": jnp.ones((c,), pd), "bias": jnp.zeros((c,), pd)}

    params: dict = {
        "stem": {
            "conv": _conv_init(next(keys), 7, 7, 3, width, pd),
            "norm": norm_params(width),
        },
        "stages": [],
    }
    cin = width
    for i, n_blocks in enumerate(cfg.stage_sizes):
        cmid = width * (2**i)
        cout = cmid * 4
        stage = []
        for j in range(n_blocks):
            block = {
                "conv1": _conv_init(next(keys), 1, 1, cin, cmid, pd),
                "norm1": norm_params(cmid),
                "conv2": _conv_init(next(keys), 3, 3, cmid, cmid, pd),
                "norm2": norm_params(cmid),
                "conv3": _conv_init(next(keys), 1, 1, cmid, cout, pd),
                "norm3": norm_params(cout),
            }
            if j == 0:
                block["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                block["proj_norm"] = norm_params(cout)
            stage.append(block)
            cin = cout
        params["stages"].append(stage)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, cfg.num_classes)) * 0.01).astype(pd),
        "b": jnp.zeros((cfg.num_classes,), pd),
    }
    return params


def resnet_forward(params: Params, images: jax.Array, cfg: ResNetConfig) -> jax.Array:
    """images [B, H, W, 3] -> logits [B, num_classes] (fp32)."""
    x = images.astype(cfg.dtype)
    stem = params["stem"]
    x = _conv(x, stem["conv"], stride=2)
    x = _group_norm(x, stem["norm"]["scale"], stem["norm"]["bias"], cfg.groups)
    x = jax.nn.relu(x)
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    for i, stage in enumerate(params["stages"]):
        for j, block in enumerate(stage):
            stride = 2 if (i > 0 and j == 0) else 1
            residual = x
            y = _conv(x, block["conv1"])
            y = _group_norm(y, block["norm1"]["scale"], block["norm1"]["bias"],
                            cfg.groups)
            y = jax.nn.relu(y)
            y = _conv(y, block["conv2"], stride=stride)
            y = _group_norm(y, block["norm2"]["scale"], block["norm2"]["bias"],
                            cfg.groups)
            y = jax.nn.relu(y)
            y = _conv(y, block["conv3"])
            y = _group_norm(y, block["norm3"]["scale"], block["norm3"]["bias"],
                            cfg.groups)
            if "proj" in block:
                residual = _conv(x, block["proj"], stride=stride)
                residual = _group_norm(
                    residual, block["proj_norm"]["scale"],
                    block["proj_norm"]["bias"], cfg.groups,
                )
            x = jax.nn.relu(y + residual)
    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    head = params["head"]
    return x @ head["w"].astype(jnp.float32) + head["b"].astype(jnp.float32)


def resnet_loss(params: Params, batch: dict, cfg: ResNetConfig) -> jax.Array:
    """Cross-entropy. batch: {'images': [B,H,W,3], 'labels': [B] int32}."""
    logits = resnet_forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(
        jnp.take_along_axis(logp, batch["labels"][:, None], axis=1)
    )
